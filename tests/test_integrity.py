"""State integrity: fingerprints, corruption detection/repair, WAL.

Covers runtime/integrity.py end to end against a real
DeviceGraphState + DeviceResidentState: the device checksum programs
must agree bit-for-bit with their host twins (zero false positives), a
single injected bit flip in ANY persistent buffer must be detected the
round it happens and repaired back to exact parity, and the WAL record
framing must classify dropped / duplicated / torn records distinctly.
"""

import numpy as np
import pytest

from ksched_tpu.graph.changes import ArcType, ChangeArcChange, NewArcChange, NodeType
from ksched_tpu.graph.device_export import DeviceGraphState, DeviceResidentState
from ksched_tpu.graph.flowgraph import FlowGraph
from ksched_tpu.runtime import integrity as ig
from ksched_tpu.runtime.chaos import ChaosPolicy, FaultInjector


def _build_state(num_tasks=12, num_machines=4, seed=0):
    g = FlowGraph()
    sink = g.add_node()
    sink.type = NodeType.SINK
    machines = [g.add_node() for _ in range(num_machines)]
    escape = g.add_node()
    tasks = [g.add_node() for _ in range(num_tasks)]
    rng = np.random.default_rng(seed)
    for m in machines:
        a = g.add_arc(m, sink)
        g.change_arc(a, 0, int(rng.integers(2, 6)), int(rng.integers(0, 4)))
    a = g.add_arc(escape, sink)
    g.change_arc(a, 0, num_tasks, 50)
    for t in tasks:
        t.excess = 1
        for m in rng.choice(num_machines, size=min(3, num_machines), replace=False):
            a = g.add_arc(t, machines[int(m)])
            g.change_arc(a, 0, 1, int(rng.integers(0, 10)))
        a = g.add_arc(t, escape)
        g.change_arc(a, 0, 1, 40)
    sink.excess = -num_tasks
    st = DeviceGraphState()
    st.full_build(g)
    return st


def _resident(st, plan=True):
    res = DeviceResidentState(st)
    if plan:
        st.plan.ensure_built()
    res.refresh()
    return res


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------


def test_host_device_fingerprints_agree():
    rng = np.random.default_rng(7)
    for arr in (
        rng.integers(-(2**31), 2**31 - 1, 1000).astype(np.int32),
        np.zeros(16, np.int32),
        rng.integers(0, 2, 64).astype(bool),
        np.arange(-50, 50, dtype=np.int32),
    ):
        dev = int(np.asarray(ig._one_fp(np.asarray(arr).astype(np.int32)))
                  .astype(np.int32).view(np.uint32))
        assert dev == ig.host_fingerprint(arr)


def test_weights_all_odd():
    # the detection guarantee rests on this: an even weight with k
    # trailing zeros makes top-k-bit flips invisible mod 2**32 (the
    # raw recurrence IS even at odd indices — regression for the
    # bit-29-at-index-15 miss the 512-round soak caught)
    assert (ig.host_weights(4096) % 2 == 1).all()


def test_single_bit_flip_always_moves_the_fingerprint():
    rng = np.random.default_rng(3)
    arr = rng.integers(-1000, 1000, 256).astype(np.int32)
    base = ig.host_fingerprint(arr)
    # exhaustive over bits at a sample of indices (incl. the soak's
    # historical miss shape: odd index, high bit)
    for i in (0, 1, 15, 17, 128, 255):
        for b in range(31):
            flipped = arr.copy()
            flipped[i] = np.int32(int(flipped[i]) ^ (1 << b))
            assert ig.host_fingerprint(flipped) != base, (i, b)
    for _ in range(64):
        i = int(rng.integers(0, len(arr)))
        b = int(rng.integers(0, 31))
        flipped = arr.copy()
        flipped[i] = np.int32(int(flipped[i]) ^ (1 << b))
        assert ig.host_fingerprint(flipped) != base, (i, b)


def test_clean_state_audits_with_zero_divergence():
    st = _build_state()
    res = _resident(st)
    auditor = ig.StateAuditor(res)
    assert auditor.audit() == []
    # ... including after a delta round
    st.apply_changes([
        ChangeArcChange(5, 1, 0, 3, 7, ArcType.OTHER, old_cost=2),
    ])
    res.refresh()
    assert auditor.audit() == []
    assert auditor.counts["divergences"] == 0


@pytest.mark.parametrize(
    "array", ["excess", "src", "dst", "cap", "cost", "p_sign", "p_arc"]
)
def test_corruption_detected_and_repaired(array):
    st = _build_state()
    res = _resident(st)
    auditor = ig.StateAuditor(res)
    assert auditor.audit() == []
    ig.apply_device_corruption(res, {"array": array, "index": 3, "bit": 5})
    diverged = auditor.audit()
    assert diverged, f"flip in {array} went undetected"
    rung = auditor.repair(diverged)
    assert rung in ig.StateAuditor.RUNGS
    # repaired back to EXACT parity with the host truth
    res.parity_check()
    res.plan_parity_check()
    assert auditor.audit() == []


def test_warm_flow_divergence_detected_and_escalates():
    """The solver's carried warm flow is solver-owned device state: a
    flip there is detected against the host copy, and repair()
    escalates straight to the caller's full_build rung (no mirror rung
    can reach it — backend.reset() is the documented fix)."""
    import jax.numpy as jnp

    st = _build_state()
    res = _resident(st)
    auditor = ig.StateAuditor(res)
    host_flow = np.arange(64, dtype=np.int32)
    clean = jnp.asarray(host_flow)
    assert auditor.audit(clean, host_flow) == []
    poisoned = ig.corrupt_fn()(clean, jnp.int32(7), jnp.int32(12))
    diverged = auditor.audit(poisoned, host_flow)
    assert diverged == ["warm_flow"]
    with pytest.raises(ig.IntegrityError, match="full_build"):
        auditor.repair(diverged)


def test_problem_row_flip_repairs_via_rescatter():
    st = _build_state()
    res = _resident(st)
    auditor = ig.StateAuditor(res)
    ig.apply_device_corruption(res, {"array": "cap", "index": 5, "bit": 2})
    rung = auditor.repair(auditor.audit())
    assert rung == "rescatter"  # O(diff) rung suffices for problem rows


def test_parity_check_raises_structured_integrity_error():
    st = _build_state()
    res = _resident(st)
    ig.apply_device_corruption(res, {"array": "cost", "index": 2, "bit": 9})
    with pytest.raises(ig.IntegrityError) as exc:
        res.parity_check()
    err = exc.value
    assert isinstance(err, AssertionError)  # bare-assert-era compat
    assert err.indices and len(err.indices) <= ig.DIFF_BOUND
    assert len(err.expected) == len(err.found) == len(err.indices)
    assert err.found != err.expected


def test_bounded_diff_is_bounded():
    got = np.arange(100, dtype=np.int32)
    want = got + 1
    err = ig.bounded_diff("x", got, want)
    assert len(err.indices) == ig.DIFF_BOUND
    assert "100 row(s)" in str(err)


def test_slot_plan_check_invariants_raises_integrity_error():
    st = _build_state()
    st.plan.ensure_built()
    st.plan.check_invariants()  # clean
    live = next(iter(st._arc_slot.values()))
    st.plan.p_sign[st.plan.pos_fwd[live]] = 0  # kill a live row behind its back
    with pytest.raises(ig.IntegrityError):
        st.plan.check_invariants()


# ---------------------------------------------------------------------------
# the injector's corruption draws
# ---------------------------------------------------------------------------


def test_device_corruption_draws_deterministic_and_counted():
    def draws(inj):
        out = []
        for _ in range(200):
            out.append(inj.device_corruption(64, 128))
        return out

    a = FaultInjector(ChaosPolicy(seed=9, device_corrupt_prob=0.2))
    b = FaultInjector(ChaosPolicy(seed=9, device_corrupt_prob=0.2))
    da, db = draws(a), draws(b)
    assert da == db
    hits = [d for d in da if d is not None]
    assert hits and a.counters["device_bit_flip"] == len(hits)
    for d in hits:
        assert d["array"] in ChaosPolicy().device_corrupt_arrays
        assert 0 <= d["bit"] < 31


def test_device_corruption_respects_availability():
    inj = FaultInjector(ChaosPolicy(seed=9, device_corrupt_prob=1.0))
    d = inj.device_corruption(64, 128, available={"cap"})
    assert d is not None and d["array"] == "cap"
    assert inj.device_corruption(64, 128, available=set()) is None


def test_checkpoint_corruption_draws():
    inj = FaultInjector(ChaosPolicy(seed=2, wal_corrupt_prob=1.0))
    kind, seed = inj.checkpoint_corruption()
    assert kind in ("wal_drop", "wal_dup", "wal_torn")
    assert inj.counters[kind] == 1
    inj.quiesce()
    assert inj.checkpoint_corruption() is None


# ---------------------------------------------------------------------------
# WAL framing
# ---------------------------------------------------------------------------


def test_wal_round_trip(tmp_path):
    p = str(tmp_path / "m.wal")
    recs = [("meta", b'{"version":1}'), ("core", b"x" * 4096), ("warm", b"")]
    ig.write_records(p, recs)
    assert ig.read_records(p) == recs


@pytest.mark.parametrize("mode", ["wal_drop", "wal_dup", "wal_torn"])
def test_wal_corruption_always_detected(tmp_path, mode):
    p = str(tmp_path / "m.wal")
    recs = [("meta", b'{"version":1}'), ("core", b"x" * 1000), ("warm", b"y" * 64)]
    kinds = set()
    for seed in range(12):
        ig.write_records(p, recs)
        ig.corrupt_wal_file(p, mode, np.random.default_rng(seed))
        with pytest.raises(ig.WALCorrupted) as exc:
            ig.read_records(p)
        kinds.add(exc.value.kind)
    expected = {"wal_drop": "seq_gap", "wal_dup": "seq_dup", "wal_torn": "truncated"}
    assert expected[mode] in kinds


def test_wal_bad_magic(tmp_path):
    p = str(tmp_path / "m.wal")
    with open(p, "wb") as f:
        f.write(b"not a wal at all")
    with pytest.raises(ig.WALCorrupted) as exc:
        ig.read_records(p)
    assert exc.value.kind == "bad_magic"
