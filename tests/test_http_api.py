"""End-to-end over real sockets: FakeAPIServer <- HTTPClusterAPI <-
SchedulerService. The informer-shaped watch loops must surface pods and
nodes, the scheduler must place them, and the Binding subresource POSTs
must land server-side (reference: k8s/k8sclient/client.go informers +
AssignBinding, run against a bare kube-apiserver per README.md:55-70)."""

import time

import pytest

from ksched_tpu.cli import SchedulerService
from ksched_tpu.cluster import Binding, FakeAPIServer, HTTPClusterAPI


@pytest.fixture
def server():
    s = FakeAPIServer().start()
    yield s
    s.stop()


def test_watch_surfaces_pods_and_nodes(server):
    api = HTTPClusterAPI(server.base_url, poll_interval_s=0.05)
    try:
        server.add_node("node_a", cores=2, pus_per_core=2)
        server.add_node("node_skip", unschedulable=True)
        server.create_pods(3)
        nodes = api.get_node_batch(timeout_s=0.3)
        assert [n.node_id for n in nodes] == ["node_a"]  # unschedulable skipped
        assert nodes[0].num_cores == 2 and nodes[0].pus_per_core == 2
        pods = api.get_pod_batch(timeout_s=0.3)
        assert sorted(p.pod_id for p in pods) == ["pod_0", "pod_1", "pod_2"]
    finally:
        api.close()


def test_binding_post_lands_and_pod_leaves_pending(server):
    api = HTTPClusterAPI(server.base_url, poll_interval_s=0.05)
    try:
        server.create_pods(2)
        api.get_pod_batch(timeout_s=0.3)
        api.assign_bindings([Binding("pod_0", "node_x")])
        assert server.bindings() == {"pod_0": "node_x"}
        assert server.pending_pods() == 1
    finally:
        api.close()


def test_redelivered_pod_does_not_duplicate_task(server):
    """A pod re-surfaced by the watch (e.g. after a failed binding POST)
    must not create a second task — and its binding must be re-emitted
    on the next round."""
    from ksched_tpu.cluster import PodEvent, SyntheticClusterAPI

    api = SyntheticClusterAPI()
    svc = SchedulerService(api, max_tasks_per_pu=1)
    svc.init_topology(fake_machines=2)
    svc.run_once([PodEvent(pod_id="pod_x")])
    assert len(svc.pod_to_task) == 1
    tid = svc.pod_to_task["pod_x"]
    assert tid in svc.old_bindings
    # re-delivery: same pod again
    emitted = svc.run_once([PodEvent(pod_id="pod_x")])
    assert len(svc.pod_to_task) == 1  # no duplicate task
    assert svc.pod_to_task["pod_x"] == tid
    assert emitted == 1  # the binding was re-posted


def test_cli_one_shot_against_http_server(server):
    """The full binary surface over HTTP: ksched-tpu --api-server URL
    --podgen N --one-shot — pods created via the API server (podgen
    parity), scheduled, bindings POSTed back."""
    from ksched_tpu.cli import main

    for i in range(2):
        server.add_node(f"node_{i}", cores=1, pus_per_core=2)
    rc = main([
        "--api-server", server.base_url,
        "--podgen", "4", "--one-shot",
        "--node-batch-timeout", "0.4",
        "--pod-batch-timeout", "0.3",
        "--max-tasks-per-pu", "1",
    ])
    assert rc == 0
    deadline = time.monotonic() + 2
    while time.monotonic() < deadline and len(server.bindings()) < 4:
        time.sleep(0.05)
    assert len(server.bindings()) == 4
    assert server.pending_pods() == 0


def test_scheduler_service_end_to_end_over_http(server):
    for i in range(3):
        server.add_node(f"node_{i}", cores=1, pus_per_core=2)
    api = HTTPClusterAPI(server.base_url, poll_interval_s=0.05)
    try:
        svc = SchedulerService(api, max_tasks_per_pu=1)
        svc.init_topology(node_batch_timeout_s=0.4)
        server.create_pods(5)  # podgen side-door
        svc.run(pod_batch_timeout_s=0.3, max_rounds=1)
        # placements arrived at the control plane as Binding POSTs
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline and len(server.bindings()) < 5:
            time.sleep(0.05)
        got = server.bindings()
        assert len(got) == 5
        assert all(v.startswith("node_") for v in got.values())
        assert server.pending_pods() == 0
    finally:
        api.close()


def test_seen_pods_reconciled_and_recreated_pod_resurfaces(server):
    """_seen_pods must track the pending listing (bounded, lock-guarded):
    a bound pod is forgotten, and a pod later re-created with the same
    name re-enters a batch instead of being filtered forever."""
    api = HTTPClusterAPI(server.base_url, poll_interval_s=0.05)
    try:
        server.create_pods(1)  # pod_0
        batch = api.get_pod_batch(timeout_s=0.5)
        assert [p.pod_id for p in batch] == ["pod_0"]
        api.assign_bindings([Binding("pod_0", "node_x")])
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline and api._seen_pods:
            time.sleep(0.02)
        assert not api._seen_pods  # reconciled away once off the listing
        # simulate delete + re-create with the same name: the binding
        # disappears server-side and the pod is pending again
        with server._state.lock:
            server._state.bindings.pop("pod_0")
        batch = api.get_pod_batch(timeout_s=0.5)
        assert [p.pod_id for p in batch] == ["pod_0"]  # re-surfaced
    finally:
        api.close()
