"""Scheduler-level backend parity: the JAX push-relabel backend must
produce placements equivalent to the exact CPU oracle through the full
event loop (equal placement counts and equal flow objective every round
— MCMF optima are non-unique so individual assignments may differ)."""

import numpy as np

from ksched_tpu.data import TaskState
from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.solver.jax_solver import JaxSolver
from ksched_tpu.utils import seed_rng


def drive(backend, seed=123):
    seed_rng(seed)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=3, num_cores=2, pus_per_core=1, max_tasks_per_pu=1, backend=backend
    )
    trace = []
    add_job(sched, jmap, tmap, num_tasks=4)
    add_job(sched, jmap, tmap, num_tasks=3)
    n, _ = sched.schedule_all_jobs()
    trace.append(("round1", n, len(sched.get_task_bindings())))

    add_job(sched, jmap, tmap, num_tasks=2)
    n, _ = sched.schedule_all_jobs()
    trace.append(("round2", n, len(sched.get_task_bindings())))

    running = sorted(
        (td for td in tmap.unsafe_get().values() if td.state == TaskState.RUNNING),
        key=lambda td: td.uid,
    )[:2]
    for td in running:
        sched.handle_task_completion(td)
    n, _ = sched.schedule_all_jobs()
    trace.append(("round3", n, len(sched.get_task_bindings())))
    n, _ = sched.schedule_all_jobs()
    trace.append(("round4", n, len(sched.get_task_bindings())))
    return trace


def test_jax_backend_matches_oracle_through_scheduler():
    ref_trace = drive(None)  # default ReferenceSolver
    jax_trace = drive(JaxSolver())
    assert ref_trace == jax_trace


def test_jax_backend_incremental_rounds_stay_consistent():
    seed_rng(99)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=4, num_cores=1, pus_per_core=2, max_tasks_per_pu=1, backend=JaxSolver()
    )
    placed_total = 0
    for i in range(6):
        add_job(sched, jmap, tmap, num_tasks=2)
        n, _ = sched.schedule_all_jobs()
        placed_total += n
        live = len(sched.gm.task_to_node)
        assert sched.gm.sink_node.excess == -live
    assert placed_total == 8  # 8 slots, 12 tasks submitted
    assert len(sched.get_task_bindings()) == 8
