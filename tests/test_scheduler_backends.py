"""Scheduler-level backend parity: the JAX push-relabel backend must
produce placements equivalent to the exact CPU oracle through the full
event loop (equal placement counts and equal flow objective every round
— MCMF optima are non-unique so individual assignments may differ)."""

import numpy as np

from ksched_tpu.data import TaskState
from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.solver.jax_solver import JaxSolver
from ksched_tpu.utils import seed_rng


def drive(backend, seed=123):
    seed_rng(seed)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=3, num_cores=2, pus_per_core=1, max_tasks_per_pu=1, backend=backend
    )
    trace = []
    add_job(sched, jmap, tmap, num_tasks=4)
    add_job(sched, jmap, tmap, num_tasks=3)
    n, _ = sched.schedule_all_jobs()
    trace.append(("round1", n, len(sched.get_task_bindings())))

    add_job(sched, jmap, tmap, num_tasks=2)
    n, _ = sched.schedule_all_jobs()
    trace.append(("round2", n, len(sched.get_task_bindings())))

    running = sorted(
        (td for td in tmap.unsafe_get().values() if td.state == TaskState.RUNNING),
        key=lambda td: td.uid,
    )[:2]
    for td in running:
        sched.handle_task_completion(td)
    n, _ = sched.schedule_all_jobs()
    trace.append(("round3", n, len(sched.get_task_bindings())))
    n, _ = sched.schedule_all_jobs()
    trace.append(("round4", n, len(sched.get_task_bindings())))
    return trace


def test_jax_backend_matches_oracle_through_scheduler():
    ref_trace = drive(None)  # default ReferenceSolver
    jax_trace = drive(JaxSolver())
    assert ref_trace == jax_trace


def test_jax_backend_incremental_rounds_stay_consistent():
    seed_rng(99)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=4, num_cores=1, pus_per_core=2, max_tasks_per_pu=1, backend=JaxSolver()
    )
    placed_total = 0
    for i in range(6):
        add_job(sched, jmap, tmap, num_tasks=2)
        n, _ = sched.schedule_all_jobs()
        placed_total += n
        live = len(sched.gm.task_to_node)
        assert sched.gm.sink_node.excess == -live
    assert placed_total == 8  # 8 slots, 12 tasks submitted
    assert len(sched.get_task_bindings()) == 8


# ---------------------------------------------------------------------------
# automatic dense-vs-CSR dispatch (solver/graph_collapse.py AutoSolver)
# ---------------------------------------------------------------------------


def drive_obj(backend, seed=123, preemption=False, cost_model_factory=None):
    """drive() plus the per-round solver objective (optimality probe)."""
    from ksched_tpu.drivers import build_cluster

    seed_rng(seed)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=3, num_cores=2, pus_per_core=1, max_tasks_per_pu=1,
        backend=backend, preemption=preemption,
        cost_model_factory=cost_model_factory,
    )
    trace = []
    add_job(sched, jmap, tmap, num_tasks=4)
    n, _ = sched.schedule_all_jobs()
    trace.append((n, len(sched.get_task_bindings()),
                  sched.solver.last_result.objective))
    add_job(sched, jmap, tmap, num_tasks=3)
    n, _ = sched.schedule_all_jobs()
    trace.append((n, len(sched.get_task_bindings()),
                  sched.solver.last_result.objective))
    running = sorted(
        (td for td in tmap.unsafe_get().values()
         if td.state == TaskState.RUNNING),
        key=lambda td: td.uid,
    )[:2]
    for td in running:
        sched.handle_task_completion(td)
    n, _ = sched.schedule_all_jobs()
    trace.append((n, len(sched.get_task_bindings()),
                  sched.solver.last_result.objective))
    return trace, sched


def test_auto_backend_goes_dense_and_matches_oracle():
    """Collapsible graphs (the trivial model's whole lifecycle,
    including lower-bound-folded pinned tasks) ride the dense transport
    with placements AND objectives identical to the CSR oracle."""
    from ksched_tpu.solver.cpu_ref import ReferenceSolver
    from ksched_tpu.solver.graph_collapse import AutoSolver

    ref_trace, _ = drive_obj(None)
    auto = AutoSolver(ReferenceSolver())
    auto_trace, _ = drive_obj(auto)
    assert auto.last_path == "dense", auto.last_refusal
    assert auto_trace == ref_trace


def test_auto_backend_binding_interior_ec_routes_csr():
    """A policy with a BINDING interior EC capacity — the one structure
    the dense collapse cannot express (docs/solver_coverage.md) — must
    route to the CSR backend automatically, with the CSR result's
    optimality intact (same trace as the pure oracle)."""
    from typing import List, Tuple

    from ksched_tpu.costmodels import TrivialCostModel
    from ksched_tpu.solver.cpu_ref import ReferenceSolver
    from ksched_tpu.solver.graph_collapse import AutoSolver

    JOB_EC, RACK_EC = 881_001, 881_002

    class BindingChainModel(TrivialCostModel):
        """task -> JOB_EC -> RACK_EC -> machines with a chain arc that
        CAN bind (cap 2 < the job's 4 tasks)."""

        def get_task_equiv_classes(self, task_id: int) -> List[int]:
            return [JOB_EC]

        def get_equiv_class_to_equiv_classes_arcs(self, ec: int) -> List[int]:
            return [RACK_EC] if ec == JOB_EC else []

        def equiv_class_to_equiv_class(self, ec1: int, ec2: int):
            return 1, 2  # cost 1, capacity 2: BINDS under 4 tasks

        def get_outgoing_equiv_class_pref_arcs(self, ec: int) -> List[int]:
            return list(self._machines) if ec == RACK_EC else []

        def task_to_equiv_class_aggregator(self, task_id: int, ec: int):
            return 2

    ref_trace, _ = drive_obj(None, cost_model_factory=BindingChainModel)
    auto = AutoSolver(ReferenceSolver())
    auto_trace, _ = drive_obj(auto, cost_model_factory=BindingChainModel)
    assert auto.last_path == "csr"
    assert "bind" in auto.last_refusal, auto.last_refusal
    assert auto_trace == ref_trace

    # the CHAIN-FED variant: ample first hop, binding cap on the
    # downstream EC's machine arcs — the r4 review's counterexample
    # (an inflow bound counting only direct task arcs would see 0 at
    # the chain-fed EC and wave the binding cap through). The audit is
    # per-solve: round 1 (4 tasks vs cap-1 arcs) must refuse; later
    # rounds with a small backlog may legitimately collapse.
    class BindingDownstreamModel(BindingChainModel):
        def equiv_class_to_equiv_class(self, ec1, ec2):
            return 1, 64  # ample chain

        def equiv_class_to_resource_node(self, ec, resource_id):
            return 1, 1  # cap 1 per machine arc: BINDS under 4 tasks

    ref2, _ = drive_obj(None, cost_model_factory=BindingDownstreamModel)
    auto2 = AutoSolver(ReferenceSolver())
    auto2_trace, sched2 = drive_obj(
        auto2, cost_model_factory=BindingDownstreamModel
    )
    assert auto2_trace == ref2
    # replay round 1's shape directly: fresh job, binding caps
    from ksched_tpu.utils import seed_rng as _seed

    _seed(123)
    from ksched_tpu.drivers import build_cluster as _bc

    auto3 = AutoSolver(ReferenceSolver())
    s3, _r, j3, t3, _root = _bc(
        num_machines=3, num_cores=2, pus_per_core=1, max_tasks_per_pu=1,
        backend=auto3, cost_model_factory=BindingDownstreamModel,
    )
    add_job(s3, j3, t3, num_tasks=4)
    s3.schedule_all_jobs()
    assert auto3.last_path == "csr"
    assert "bind" in auto3.last_refusal, auto3.last_refusal


def test_auto_backend_keep_mode_routes_csr():
    """Preemption-on (keep-arcs) graphs carry per-task running arcs to
    leaves — outside the dense shape — and must route to CSR once
    tasks are running."""
    from ksched_tpu.solver.cpu_ref import ReferenceSolver
    from ksched_tpu.solver.graph_collapse import AutoSolver

    ref_trace, _ = drive_obj(None, preemption=True)
    auto = AutoSolver(ReferenceSolver())
    auto_trace, _ = drive_obj(auto, preemption=True)
    assert auto.last_path == "csr"
    assert auto_trace == ref_trace


def test_try_collapse_structural_refusals():
    """Direct structural edge cases of the collapse audit: a diamond
    below one machine (double-counted capacity / non-tree), and a
    machine whose two sink paths carry different total costs, must
    both REFUSE — not crash, not collapse."""
    from ksched_tpu.graph.device_export import FlowProblem
    from ksched_tpu.graph.flowgraph import NodeType
    from ksched_tpu.solver.graph_collapse import try_collapse

    def make(node_types, arcs, excesses):
        """node ids start at 1 (row 0 padding)."""
        N = len(node_types) + 1
        nt = np.full(N, -1, np.int8)
        ex = np.zeros(N, np.int64)
        for i, t in enumerate(node_types, start=1):
            nt[i] = int(t)
        for i, e in excesses.items():
            ex[i] = e
        src = np.array([a[0] for a in arcs], np.int32)
        dst = np.array([a[1] for a in arcs], np.int32)
        cap = np.array([a[2] for a in arcs], np.int32)
        cost = np.array([a[3] for a in arcs], np.int32)
        return FlowProblem(
            num_nodes=N, excess=ex, node_type=nt, src=src, dst=dst,
            cap=cap, cost=cost,
            flow_offset=np.zeros(len(arcs), np.int32),
            num_arcs=len(arcs),
        )

    T = NodeType
    # nodes: 1=sink, 2=task, 3=agg, 4=machine, 5=PU-a, 6=PU-b
    base_types = [T.SINK, T.UNSCHEDULED_TASK, T.JOB_AGGREGATOR,
                  T.MACHINE, T.PU, T.PU]

    # diamond: machine -> PU-a twice (two parallel arcs into the same
    # subtree) — capacity must NOT double-count; audit refuses
    p = make(
        base_types,
        [(2, 3, 1, 7), (3, 1, 4, 0), (2, 4, 1, 2),
         (4, 5, 1, 0), (4, 5, 1, 0), (5, 1, 1, 0)],
        {2: 1, 1: -1},
    )
    gc, reason = try_collapse(p)
    assert gc is None and "non-tree" in reason, reason

    # non-uniform path costs: machine -> PU-a (cost 0) -> sink and
    # machine -> PU-b (cost 3) -> sink give the column two different
    # totals; audit refuses
    p = make(
        base_types,
        [(2, 3, 1, 7), (3, 1, 4, 0), (2, 4, 1, 2),
         (4, 5, 1, 0), (4, 6, 1, 3), (5, 1, 1, 0), (6, 1, 1, 0)],
        {2: 1, 1: -1},
    )
    gc, reason = try_collapse(p)
    assert gc is None and "non-uniform" in reason, reason

    # the well-formed twin of the same shape COLLAPSES (sanity: the
    # refusals above are about the defects, not the harness)
    p = make(
        base_types,
        [(2, 3, 1, 7), (3, 1, 4, 0), (2, 4, 1, 2),
         (4, 5, 1, 0), (4, 6, 1, 0), (5, 1, 1, 0), (6, 1, 1, 0)],
        {2: 1, 1: -1},
    )
    gc, reason = try_collapse(p)
    assert gc is not None, reason
    assert gc.col_cap.tolist() == [2]  # two PU slots under one machine
    assert gc.row_unsched.tolist() == [7]


def test_try_collapse_refuses_pathologically_deep_subtree():
    """A machine subtree deeper than the Python recursion limit must
    REFUSE ('graph too deep'), not escape as RecursionError — the
    refusal contract says every unauditable input falls back to CSR."""
    import sys

    from ksched_tpu.graph.device_export import FlowProblem
    from ksched_tpu.graph.flowgraph import NodeType
    from ksched_tpu.solver.graph_collapse import try_collapse

    T = NodeType
    depth = sys.getrecursionlimit() + 200
    # nodes: 1=sink, 2=task, 3=agg, 4=machine, 5..5+depth-1 = PU chain
    node_types = [T.SINK, T.UNSCHEDULED_TASK, T.JOB_AGGREGATOR, T.MACHINE]
    node_types += [T.PU] * depth
    N = len(node_types) + 1
    nt = np.full(N, -1, np.int8)
    for i, t in enumerate(node_types, start=1):
        nt[i] = int(t)
    ex = np.zeros(N, np.int64)
    ex[2], ex[1] = 1, -1
    arcs = [(2, 3, 1, 7), (3, 1, 4, 0), (2, 4, 1, 2), (4, 5, 1, 0)]
    for i in range(depth - 1):
        arcs.append((5 + i, 5 + i + 1, 1, 0))
    arcs.append((5 + depth - 1, 1, 1, 0))
    p = FlowProblem(
        num_nodes=N, excess=ex, node_type=nt,
        src=np.array([a[0] for a in arcs], np.int32),
        dst=np.array([a[1] for a in arcs], np.int32),
        cap=np.array([a[2] for a in arcs], np.int32),
        cost=np.array([a[3] for a in arcs], np.int32),
        flow_offset=np.zeros(len(arcs), np.int32),
        num_arcs=len(arcs),
    )
    gc, reason = try_collapse(p)
    assert gc is None and "too deep" in reason, reason


def test_auto_solver_reports_csr_supersteps_of_zero():
    """A CSR fallback whose solve legitimately took 0 supersteps must
    report 0 — not fall through to a stale last_iterations value."""
    from ksched_tpu.solver.graph_collapse import AutoSolver

    class FakeCsr:
        last_supersteps = 0
        last_iterations = 99  # stale, differently-scaled

        def reset(self):
            pass

        def solve(self, problem):
            return "fake-result"

    from ksched_tpu.graph.device_export import FlowProblem
    from ksched_tpu.graph.flowgraph import NodeType

    # two sinks: the audit refuses instantly, routing to the fake CSR
    nt = np.full(3, -1, np.int8)
    nt[1] = nt[2] = int(NodeType.SINK)
    p = FlowProblem(
        num_nodes=3, excess=np.zeros(3, np.int64), node_type=nt,
        src=np.zeros(0, np.int32), dst=np.zeros(0, np.int32),
        cap=np.zeros(0, np.int32), cost=np.zeros(0, np.int32),
        flow_offset=np.zeros(0, np.int32), num_arcs=0,
    )
    auto = AutoSolver(FakeCsr())
    assert auto.solve(p) == "fake-result"
    assert auto.last_path == "csr"
    assert auto.last_supersteps == 0


def test_ell_backend_matches_oracle_through_scheduler():
    """The bucketed-ELL layout (solver/ell_solver.py) through the full
    event loop: same placement counts and binding totals as the oracle
    every round — the graph-path drop-in contract for `--backend ell`."""
    from ksched_tpu.solver.ell_solver import EllSolver

    ref_trace = drive(None)
    ell_trace = drive(EllSolver(w_hub=16))
    assert ref_trace == ell_trace
