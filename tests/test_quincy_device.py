"""Quincy on the device fast path: group-mode DeviceBulkCluster +
QuincyGroupTable vs the host graph path (per-task preference arcs via
GetTaskPreferenceArcs wiring + the exact SSP oracle).

Parity contract: both sides solve the same policy (route via the class
EC at worst-case transfer cost vs direct preference arcs at local
transfer cost; escape at worst+1), so with both solvers exact the
REALIZED TOTAL COST must be equal — assignments may differ among
cost-equal optima.
"""

import numpy as np
import pytest

from ksched_tpu.costmodels.quincy import QuincyCostModel
from ksched_tpu.costmodels.quincy_device import PREF_NONE, QuincyGroupTable
from ksched_tpu.data import ReferenceDescriptor, ReferenceType
from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
from ksched_tpu.utils import resource_id_from_string

MB = 1 << 20


# ---------------------------------------------------------------------------
# group table semantics
# ---------------------------------------------------------------------------


def test_group_table_dedupes_signatures():
    t = QuincyGroupTable(num_groups=8, num_machines=4)
    t.blocks.register(1, 512 * MB, [0])
    t.blocks.register(2, 512 * MB, [1])
    g1 = t.group_for(0, [1])
    g1b = t.group_for(0, [1])
    g2 = t.group_for(0, [2])
    g_none = t.group_for(0, [])
    assert g1 == g1b
    assert g1 != g2
    assert g_none == 0  # the class-0 fallback group
    # group 1's preference: machine 0 at transfer cost 0 (fully local)
    assert t.pref_w[g1, 0] == 0
    assert (t.pref_w[g1, 1:] == PREF_NONE).all()
    assert t.e[g1] == 512  # worst case: 512 MB remote
    assert t.u[g1] == 513


def test_group_table_overflow_goes_to_priced_overflow_group():
    # 1 class: group 0 = no-input fallback, group 1 = overflow, group 2
    # = the one free signature slot
    t = QuincyGroupTable(num_groups=3, num_machines=4)
    t.blocks.register(1, 512 * MB, [0])
    t.blocks.register(2, 256 * MB, [1])
    g1 = t.group_for(0, [1])
    assert g1 == 2
    g2 = t.group_for(0, [2])  # table full -> class overflow group
    assert g2 == 1
    assert t.overflowed == 1
    # overflow pricing is conservative: the costliest overflowed
    # signature's worst-case transfer, never an undercharge
    assert t.e[1] == 256 and t.u[1] == 257
    assert (t.pref_w[1] == t.pref_w[0]).all()  # no preferences


def test_group_table_lru_eviction_reclaims_and_reuses():
    """evict_idle reclaims zero-live groups LRU-first; freed gids are
    reused BEFORE overflowing, and an overflowed signature can register
    properly after eviction frees room."""
    t = QuincyGroupTable(num_groups=4, num_machines=4)
    # 1 class -> gids 0 (fallback), 1 (overflow), 2..3 dynamic
    t.blocks.register(1, 512 * MB, [0])
    t.blocks.register(2, 256 * MB, [1])
    t.blocks.register(3, 128 * MB, [2])
    g1 = t.group_for(0, [1])
    g2 = t.group_for(0, [2])
    assert {g1, g2} == {2, 3}
    g3 = t.group_for(0, [3])  # full -> overflow
    assert g3 == 1 and t.overflowed == 1

    # g1 has live tasks, g2 idle; touch g1 so g2 is also the LRU
    t.group_for(0, [1])
    live = np.zeros(4, np.int64)
    live[g1] = 5
    n = t.evict_idle(live, keep_fraction=0.0)
    assert n == 1 and t.evicted == 1
    assert (t.pref_w[g2] == PREF_NONE).all()
    # signature 3 was only memoized to the overflow gid; a NEW distinct
    # signature reuses the freed slot instead of overflowing
    t.blocks.register(4, 64 * MB, [3])
    g4 = t.group_for(0, [4])
    assert g4 == g2  # reused the evicted gid
    assert t.pref_w[g4, 3] == 0 and t.e[g4] == 64
    # signature 2 re-registers fresh after its eviction (not stale-mapped)
    live2 = np.zeros(4, np.int64)
    live2[g1] = 5
    live2[g4] = 1
    assert t.evict_idle(live2, keep_fraction=1.0) == 0  # under target
    g2b = t.group_for(0, [2])
    assert g2b == 1  # table full again -> overflow (g2's slot is taken)


def test_group_table_split_quanta_semantics():
    """sig_unit_mb coarser than cost_unit_mb: near-identical templates
    merge into one group while stored costs keep cost-unit resolution;
    a nonzero-cost no-preference template must NOT collapse onto the
    zero-cost fallback group; overflow pricing stays conservative
    across merged templates; finer sig than cost is rejected."""
    t = QuincyGroupTable(
        num_groups=6, num_machines=4, cost_unit_mb=1, sig_unit_mb=128
    )
    # two templates whose costs differ by < one sig quantum merge
    t.blocks.register(1, 512 * MB, [0])
    t.blocks.register(2, 513 * MB, [0])
    g1 = t.group_for(0, [1])
    g2 = t.group_for(0, [2])
    assert g1 == g2
    assert t.e[g1] == 512  # first registrant's cost-unit values
    # a 100 MB orphaned block (no holders above threshold): sig-worst
    # floors to 0 but the TRUE cost is 100 — must get its own group,
    # not the free fallback
    t.blocks.register(3, 100 * MB, [])
    g3 = t.group_for(0, [3])
    assert g3 != 0 and t.e[g3] == 100 and t.u[g3] == 101
    # genuinely-zero template still takes the fallback
    assert t.group_for(0, []) == 0

    with pytest.raises(ValueError):
        QuincyGroupTable(
            num_groups=4, num_machines=2, cost_unit_mb=64, sig_unit_mb=1
        )


def test_group_table_overflow_ratchet_covers_merged_templates():
    """With split quanta, templates merged into one overflow signature
    can differ by up to a sig quantum; the overflow price must ratchet
    on memoized hits too (never undercharge)."""
    t = QuincyGroupTable(
        num_groups=2, num_machines=4, cost_unit_mb=1, sig_unit_mb=128
    )
    # G=2 = fallback + overflow only: everything nonzero overflows
    t.blocks.register(1, 512 * MB, [0])
    t.blocks.register(2, 600 * MB, [0])  # same sig bucket (512//128 == 600//128)
    g1 = t.group_for(0, [1])
    assert g1 == 1 and t.e[1] == 512
    g2 = t.group_for(0, [2])  # memoized-sig hit on the overflow gid
    assert g2 == 1
    assert t.e[1] == 600 and t.u[1] == 601  # ratcheted to the dearer worst


def test_group_table_overflow_unpins_after_eviction():
    """A signature that first appeared under table pressure (memoized
    to the overflow gid) must register PROPERLY once eviction frees
    room — overflow pinning is pressure-scoped, not permanent."""
    t = QuincyGroupTable(num_groups=4, num_machines=4)
    t.blocks.register(1, 512 * MB, [0])
    t.blocks.register(2, 256 * MB, [1])
    t.blocks.register(3, 128 * MB, [2])
    g1 = t.group_for(0, [1])
    g2 = t.group_for(0, [2])
    g3 = t.group_for(0, [3])  # table full -> overflow, sig pinned
    assert g3 == 1
    # overflow price ratcheted to the overflowed signature's worst
    assert t.e[1] == 128
    live = np.zeros(4, np.int64)
    live[g1] = 2  # g2 idle -> evictable; overflow row idle too
    assert t.evict_idle(live, keep_fraction=0.0) == 1
    # idle overflow row's conservative ratchet reset
    assert t.e[1] == 0 and t.u[1] == 1
    g3b = t.group_for(0, [3])
    assert g3b == g2  # re-registered properly in the freed slot
    assert t.pref_w[g3b, 2] == 0 and t.e[g3b] == 128


def test_group_table_drop_machine_prunes_prefs():
    t = QuincyGroupTable(num_groups=8, num_machines=4)
    t.blocks.register(1, 512 * MB, [2])
    g = t.group_for(0, [1])
    assert t.pref_w[g, 2] == 0
    t.drop_machine(2)
    assert t.pref_w[g, 2] == PREF_NONE


def test_group_table_wait_aging():
    t = QuincyGroupTable(num_groups=4, num_machines=2)
    t.blocks.register(1, 256 * MB, [0])
    g = t.group_for(0, [1])
    u0 = t.effective_u()[g]
    t.bump_wait(np.eye(1, 4, g, dtype=np.int64)[0])
    assert t.effective_u()[g] == u0 + t.wait_cost_per_round
    t.bump_wait(np.zeros(4, np.int64))  # backlog cleared -> reset
    assert t.effective_u()[g] == u0


# ---------------------------------------------------------------------------
# parity with the host graph path
# ---------------------------------------------------------------------------


def _host_quincy_realized_cost(num_machines, slots_per_machine, task_blocks,
                               block_locs, block_size):
    """Drive the host graph path (FlowScheduler + QuincyCostModel +
    exact oracle) and return (realized_total_cost, num_placed).
    task_blocks: list of block-id lists (one per task); block_locs:
    block id -> machine indices."""
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=num_machines,
        num_cores=1,
        pus_per_core=slots_per_machine,
        max_tasks_per_pu=1,
        cost_model_factory=QuincyCostModel,
    )
    model: QuincyCostModel = sched.cost_model
    machines = list(model._machines.keys())  # resource ids, machine order
    for b, locs in block_locs.items():
        model.blocks.register(b, block_size, [machines[m] for m in locs])
    job = add_job(sched, jmap, tmap, num_tasks=len(task_blocks))
    task_ids = [t for t, td in tmap.items() if td.job_id == str(job)]
    for tid, blocks in zip(task_ids, task_blocks):
        td = tmap.find(tid)
        for b in blocks:
            td.dependencies.append(
                ReferenceDescriptor(
                    id=b, type=ReferenceType.CONCRETE, size=block_size
                )
            )
    n, _ = sched.schedule_all_jobs()

    # realized cost: placed -> cheapest available route to the bound
    # machine (pref arc if wired there, else the EC route at worst);
    # unplaced -> escape cost
    bindings = sched.get_task_bindings()
    total_cost = 0
    for tid in task_ids:
        total, local = model._input_bytes(tid)
        worst = model._transfer_cost(total, 0)
        pu_rid = bindings.get(tid)
        if pu_rid is None:
            total_cost += worst + 1  # task_to_unscheduled_agg_cost, wait=0
            continue
        node = rmap.find(pu_rid).topology_node
        while node.resource_desc.type.name != "MACHINE":
            node = rmap.find(
                resource_id_from_string(node.parent_id)
            ).topology_node
        m_rid = resource_id_from_string(node.resource_desc.uuid)
        direct = model._transfer_cost(total, local.get(m_rid, 0))
        prefs = set(model.get_task_preference_arcs(tid))
        total_cost += min(worst, direct) if m_rid in prefs else worst
    return total_cost, n


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_quincy_device_objective_matches_graph_path(seed):
    rng = np.random.default_rng(seed)
    M, S = 4, 2  # 4 machines x (1 core x 2 PUs x 1 slot) = 8 slots
    B = 5
    block_size = 512 * MB
    block_locs = {b: sorted(
        rng.choice(M, size=int(rng.integers(1, 3)), replace=False).tolist()
    ) for b in range(1, B + 1)}
    n_tasks = 10  # 10 tasks onto 8 slots -> 2 stay unscheduled
    task_blocks = []
    for _ in range(n_tasks):
        k = int(rng.integers(0, 3))
        task_blocks.append(
            sorted(rng.choice(np.arange(1, B + 1), size=k, replace=False).tolist())
        )

    host_cost, host_placed = _host_quincy_realized_cost(
        M, S, task_blocks, block_locs, block_size
    )

    table = QuincyGroupTable(num_groups=32, num_machines=M)
    for b, locs in block_locs.items():
        table.blocks.register(b, block_size, locs)
    groups = table.groups_for(
        np.zeros(n_tasks, np.int32), task_blocks
    )
    dev = DeviceBulkCluster(
        num_machines=M, pus_per_machine=S, slots_per_pu=1, num_jobs=1,
        num_task_classes=1, task_capacity=32, num_groups=32,
    )
    table.sync(dev)
    dev.add_tasks(n_tasks, groups=groups)
    stats = dev.fetch_stats(dev.round())
    assert bool(stats["converged"])
    assert int(stats["placed"]) == host_placed
    assert int(stats["objective"]) == host_cost, (
        f"device objective {int(stats['objective'])} != host graph path "
        f"{host_cost}"
    )


def test_quincy_device_bounded_window_matches_full():
    """The windowed decode must agree with the full-width decode when
    the window covers the whole backlog (group mode)."""
    M = 3
    table = QuincyGroupTable(num_groups=16, num_machines=M)
    table.blocks.register(1, 512 * MB, [1])
    table.blocks.register(2, 512 * MB, [2])
    task_blocks = [[1]] * 3 + [[2]] * 3 + [[]] * 2
    groups = table.groups_for(np.zeros(8, np.int32), task_blocks)

    outs = []
    for width in (None, 16):
        dev = DeviceBulkCluster(
            num_machines=M, pus_per_machine=2, slots_per_pu=2, num_jobs=1,
            num_task_classes=1, task_capacity=16, num_groups=16,
            decode_width=width,
        )
        table.sync(dev)
        dev.add_tasks(8, groups=groups)
        dev.round()  # full-width fill round
        s = dev.fetch_stats(dev.run_steady_rounds(4, 0.3, 1, seed=7))
        assert s["converged"].all()
        outs.append((s["placed"].sum(), s["objective"][-1]))
    assert outs[0] == outs[1]


def test_active_cap_ladder_matches_full_width():
    """The compaction LADDER (a sequence of active_groups_cap widths)
    must agree with the full-width solve at every rung: rounds whose
    active-row count fits the smallest width, a middle width, and only
    the full width all produce identical objectives/placements —
    compaction is exact, the ladder only changes which static width
    carries the solve."""
    M = 4
    table = QuincyGroupTable(num_groups=16, num_machines=M)
    for b in range(1, 9):
        table.blocks.register(b, 512 * MB, [b % M])
    rng = np.random.default_rng(3)

    outs = []
    for caps in (16, (2, 6), (1, 4, 12)):
        dev = DeviceBulkCluster(
            num_machines=M, pus_per_machine=2, slots_per_pu=2, num_jobs=1,
            num_task_classes=1, task_capacity=64, num_groups=16,
            active_groups_cap=caps,
        )
        assert dev.active_groups_caps == (
            (caps,) if isinstance(caps, int) else caps
        )
        r = np.random.default_rng(3)
        # escalating diversity: 1 group, then 3, then 8 — hits the
        # small rung, a middle rung, and the full-width fallback
        placed, objs = 0, []
        for n_groups in (1, 3, 8):
            blocks = [[int(r.integers(1, n_groups + 1))] for _ in range(6)]
            groups = table.groups_for(np.zeros(6, np.int32), blocks)
            table.sync(dev)  # push rows AFTER registration
            dev.add_tasks(6, groups=groups)
            s = dev.fetch_stats(dev.round())
            assert bool(s["converged"])
            placed += int(s["placed"])
            objs.append(int(s["objective"]))
            done = np.nonzero(
                np.asarray(dev.fetch_state()["live"])
            )[0]
            dev.complete_tasks(done.astype(np.int32))
        outs.append((placed, objs))
    assert outs[0] == outs[1] == outs[2], outs


def test_quincy_device_preemption_mode_with_groups():
    """Preemption + groups: shifting a preference (data re-replicated)
    migrates residents toward the preferred machine."""
    M = 2
    dev = DeviceBulkCluster(
        num_machines=M, pus_per_machine=1, slots_per_pu=2, num_jobs=1,
        num_task_classes=1, task_capacity=8, num_groups=2,
        preemption=True, continuation_discount=1,
    )
    pref = np.full((2, M), PREF_NONE, np.int64)
    dev.set_groups(cls=[0, 0], job=[0, 0], e=[100, 100], u=[500, 500],
                   pref_w=pref)
    dev.add_tasks(2, groups=np.array([1, 1], np.int32))
    s0 = dev.fetch_stats(dev.round())
    assert int(s0["placed"]) == 2
    # data for group 1 appears on machine 1: route 100 -> pref 10
    pref[1, 1] = 10
    dev.set_groups(pref_w=pref)
    s1 = dev.fetch_stats(dev.round())
    st = dev.fetch_state()
    on = st["pu"][:2]
    assert int(s1["migrated"]) >= 1 or (on // 1 == 1).all()
    # everyone ends on machine 1 (pu index 1): pref beats continuation
    assert (on == 1).all(), on


def test_quincy_steady_shape_two_stage_exact_and_fast():
    """The steady-state regression: residents hold the preferred
    machines, the backlog is ~a hundred near-identical rows whose only
    differentiation is a few capacity-limited pref cells. The one-shot
    dense solve herds on the uniform ground cells (measured 27k-43k
    supersteps at 10k x 1k on hardware under every eps schedule); the
    grouped round must take the exact two-stage decomposition instead:
    sparse pref matching + closed-form ground fill, tens of supersteps.
    Exactness is pinned against the host layered solver on the same
    instance."""
    from ksched_tpu.solver.layered import LayeredProblem, LayeredTransportSolver

    rng = np.random.default_rng(42)
    M, P, S, G = 64, 2, 2, 24
    dev = DeviceBulkCluster(
        num_machines=M, pus_per_machine=P, slots_per_pu=S, num_jobs=1,
        num_task_classes=1, task_capacity=512, num_groups=G,
        supersteps=1 << 15,
    )
    pref = np.full((G, M), PREF_NONE, np.int64)
    e = np.full(G, 512, np.int64)
    u = np.full(G, 513, np.int64)
    for g in range(G):
        pref[g, rng.choice(M, 2, replace=False)] = 0
    dev.set_groups(cls=np.zeros(G), job=np.zeros(G), e=e, u=u, pref_w=pref)
    n0 = 200  # fill ~78% of the 256 slots
    g0 = rng.integers(0, G, n0).astype(np.int32)
    dev.add_tasks(n0, groups=g0)
    s_fill = dev.fetch_stats(dev.round())
    assert bool(s_fill["converged"])
    # churn: complete 30 residents, admit 30 new
    st = dev.fetch_state()
    placed_rows = np.nonzero(st["live"] & (st["pu"] >= 0))[0]
    dev.complete_tasks(rng.choice(placed_rows, 30, replace=False))
    g_new = rng.integers(0, G, 30).astype(np.int32)
    dev.add_tasks(30, groups=g_new)

    # capture the pre-round instance for the host oracle
    st = dev.fetch_state()
    unpl = st["live"] & (st["pu"] < 0)
    supply = np.bincount(st["grp"][unpl], minlength=G).astype(np.int32)
    free = (S - st["pu_running"]).reshape(M, P).sum(axis=1)
    cost_eff = np.minimum(e[:, None], pref)  # route vs preference

    s = dev.fetch_stats(dev.round())
    assert bool(s["converged"])
    # the decomposition does the sparse matching only: a bounded eps=1
    # attempt (<=256) plus, on this blocked shape, the full-range
    # fallback (~900 here) — far from the one-shot dense solve's
    # herding ~34k. Residual pref-contention fights are the documented
    # remaining cost (docs/NOTES.md).
    assert int(s["supersteps"]) < 2000, int(s["supersteps"])

    want = LayeredTransportSolver().solve_layered(
        LayeredProblem(
            supply=supply,
            col_cap=free.astype(np.int32),
            cost_cm=cost_eff.astype(np.int32),
            unsched_cost=0,
            ec_cost=0,
            row_unsched_cost=u,
        )
    )
    assert int(s["objective"]) == want.objective, (
        int(s["objective"]), want.objective
    )
