"""HeartbeatMonitor edge cases (all sweeps on an injected clock — no
wall-clock sleeps anywhere): a LOST machine that resumes heartbeating
after deregistration, a task unbound mid-sweep by its machine's loss,
and the RoundWatchdog deadline semantics."""

import time

import pytest

from ksched_tpu.data import ResourceState, TaskState
from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.runtime import HeartbeatMonitor, RoundWatchdog


def _machine_rids(rmap):
    return [rid for rid, rs in rmap.items() if rs.descriptor.type.name == "MACHINE"]


def _frozen_clock():
    # any sweep that forgets to pass `now` would read an absurd fixed
    # epoch and trip the assertions below — wall clock never enters
    return lambda: 1e12


def test_lost_machine_resuming_heartbeat_is_stale_not_resurrected():
    """A machine that goes LOST and is deregistered may well come back
    and keep beating (a partitioned node rejoining). The beat must be
    ignored — counted as stale — not raise, and must NOT resurrect the
    pruned machine: re-admission goes through registration."""
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=2, max_tasks_per_pu=1
    )
    add_job(sched, jmap, tmap, num_tasks=2)
    sched.schedule_all_jobs()
    mon = HeartbeatMonitor(sched, machine_timeout_s=10.0, clock=_frozen_clock())
    machines = _machine_rids(rmap)
    for m in machines:
        assert mon.record_machine_heartbeat(m, now=100.0)
    mon.record_machine_heartbeat(machines[1], now=150.0)
    lost, _ = mon.check(now=150.0)
    assert lost == [machines[0]]
    assert rmap.find(machines[0]) is None  # deregistered and pruned

    # the "dead" machine resumes beating: stale, ignored, not fatal
    assert mon.record_machine_heartbeat(machines[0], now=151.0) is False
    assert mon.stale_heartbeats == 1
    assert rmap.find(machines[0]) is None  # still gone
    lost2, _ = mon.check(now=152.0)
    assert lost2 == []  # and no repeat loss either


def test_task_heartbeat_for_retired_task_is_stale():
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=1, pus_per_core=1)
    mon = HeartbeatMonitor(sched, clock=_frozen_clock())
    assert mon.record_task_heartbeat(123456789, now=1.0) is False
    assert mon.stale_heartbeats == 1


def test_task_unbound_mid_sweep_by_machine_loss_not_double_failed():
    """One sweep, two expiries: a machine goes LOST, and a task running
    ON that machine has a stale heartbeat too. The machine's deregister
    evicts the task (back to RUNNABLE) before the task pass runs — the
    sweep must NOT also fail it (HandleTaskFailure on an unbound task
    would assert). Meanwhile a genuinely silent task on a *surviving*
    machine must still be failed."""
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=1, max_tasks_per_pu=1
    )
    add_job(sched, jmap, tmap, num_tasks=2)
    n, _ = sched.schedule_all_jobs()
    assert n == 2
    mon = HeartbeatMonitor(
        sched, machine_timeout_s=10.0, task_timeout_s=5.0, clock=_frozen_clock()
    )
    machines = _machine_rids(rmap)
    bindings = dict(sched.get_task_bindings())
    # which task lives on machines[0]? walk its subtree's bindings
    from ksched_tpu.utils import resource_id_from_string

    def tasks_on_machine(mrid):
        out = []
        stack = [rmap.find(mrid).topology_node]
        while stack:
            node = stack.pop()
            stack.extend(node.children)
            rid = resource_id_from_string(node.resource_desc.uuid)
            out.extend(sched.resource_bindings.get(rid, ()))
        return out

    doomed = tasks_on_machine(machines[0])
    assert len(doomed) == 1
    survivor_task = next(t for t in bindings if t not in doomed)

    for m in machines:
        mon.record_machine_heartbeat(m, now=100.0)
    mon.record_machine_heartbeat(machines[1], now=150.0)  # m0 goes silent
    # BOTH tasks last beat long ago — both look stale at t=150
    mon.record_task_heartbeat(doomed[0], now=100.0)
    mon.record_task_heartbeat(survivor_task, now=100.0)

    lost, failed = mon.check(now=150.0)
    assert lost == [machines[0]]
    # the machine's task was unbound mid-sweep: evicted, NOT failed
    assert failed == [survivor_task]
    assert tmap.find(doomed[0]).state == TaskState.RUNNABLE
    assert tmap.find(survivor_task).state == TaskState.FAILED
    assert doomed[0] not in sched.get_task_bindings()


def test_injected_clock_never_consults_wall_clock():
    """Sweeps with explicit `now` must be wall-clock-free end to end:
    a monitor whose fallback clock would blow every timeout detects
    nothing when the injected timeline says all is well."""
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=2, pus_per_core=1)
    add_job(sched, jmap, tmap, num_tasks=2)
    sched.schedule_all_jobs()
    mon = HeartbeatMonitor(
        sched, machine_timeout_s=1.0, task_timeout_s=1.0, clock=_frozen_clock()
    )
    for m in _machine_rids(rmap):
        mon.record_machine_heartbeat(m, now=5.0)
    for t in sched.get_task_bindings():
        mon.record_task_heartbeat(t, now=5.0)
    lost, failed = mon.check(now=5.5)  # within timeouts on the injected line
    assert lost == [] and failed == []
    # and the same state read through the frozen wall clock WOULD expire
    lost, failed = mon.check()
    assert len(lost) == 2


def test_heartbeat_at_time_zero_is_monitored():
    """A beat recorded at now=0.0 — round 0 of any logical-time driver,
    e.g. the chaos soak — must arm monitoring, not read as "never
    heartbeated" through a falsy-zero sentinel. A machine and a task
    that beat only at t=0 and then go silent must both expire."""
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=1, max_tasks_per_pu=1
    )
    add_job(sched, jmap, tmap, num_tasks=2)
    sched.schedule_all_jobs()
    mon = HeartbeatMonitor(
        sched, machine_timeout_s=10.0, task_timeout_s=5.0, clock=_frozen_clock()
    )
    machines = _machine_rids(rmap)
    for m in machines:
        assert mon.record_machine_heartbeat(m, now=0.0)
    mon.record_machine_heartbeat(machines[1], now=20.0)  # m0 silent since t=0
    for t in sched.get_task_bindings():
        assert mon.record_task_heartbeat(t, now=0.0)
    lost, failed = mon.check(now=20.0)
    assert lost == [machines[0]]  # beat at t=0 armed the timeout
    # the surviving machine's task beat only at t=0 too: silent, failed
    assert len(failed) == 1
    assert tmap.find(failed[0]).state == TaskState.FAILED


def test_round_watchdog_fires_and_counts():
    wd = RoundWatchdog(deadline_s=0.02)
    with pytest.warns(RuntimeWarning, match="deadline"):
        with wd:
            time.sleep(0.08)
        assert wd.fired
    assert wd.misses == 1
    # a fast round resets `fired` and adds no miss
    with wd:
        pass
    assert not wd.fired and wd.misses == 1


def test_round_watchdog_disabled_never_fires():
    wd = RoundWatchdog(deadline_s=0.0)
    with wd:
        time.sleep(0.01)
    assert not wd.fired and wd.misses == 0
