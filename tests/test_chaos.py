"""Chaos harness + hardened control loop: seeded fault-schedule
determinism, the solver degradation ladder (configured → jax → cpu_ref
→ NOOP), NaN'd-cost rejection, the closed-vs-outage loop fix, dropped
binding POSTs re-surfacing, and a short in-process chaos soak with
fault accounting and cross-run determinism."""

import threading
import time

import numpy as np
import pytest

from ksched_tpu.cli import SchedulerService
from ksched_tpu.cluster import PodEvent, SyntheticClusterAPI
from ksched_tpu.runtime import (
    ChaosBackendError,
    ChaosClusterAPI,
    ChaosPolicy,
    DegradingSolver,
    FaultInjector,
    LadderExhausted,
    RoundTracer,
    build_degradation_ladder,
)
from ksched_tpu.solver.base import FlowResult, FlowSolver
from ksched_tpu.solver.cpu_ref import ReferenceSolver

# -- injector determinism --------------------------------------------------


def _drive(injector, rounds=64):
    log = []
    for r in range(rounds):
        injector.begin_round(r)
        log.append((
            injector.outage_active(),
            injector.drop_binding(),
            injector.solver_fault(0),
            injector.machine_silent(7),
            injector.http_fault("bind"),
        ))
    return log


def test_same_seed_same_fault_schedule():
    policy = ChaosPolicy(
        seed=11, api_outage_prob=0.2, binding_drop_prob=0.3,
        solver_fault_prob=0.25, machine_flap_prob=0.15,
        http_error_prob=0.1, http_hang_prob=0.05, http_latency_prob=0.1,
    )
    a, b = FaultInjector(policy), FaultInjector(policy)
    assert _drive(a) == _drive(b)
    assert dict(a.counters) == dict(b.counters)
    assert sum(a.counters.values()) > 0  # the schedule actually fired


def test_different_seeds_differ():
    pol = dict(api_outage_prob=0.2, binding_drop_prob=0.3, solver_fault_prob=0.25)
    a = FaultInjector(ChaosPolicy(seed=1, **pol))
    b = FaultInjector(ChaosPolicy(seed=2, **pol))
    assert _drive(a) != _drive(b)


def test_domain_streams_independent():
    """Consuming one fault domain at a different rate must not perturb
    another domain's schedule (per-domain RNG streams)."""
    policy = ChaosPolicy(seed=5, binding_drop_prob=0.3, solver_fault_prob=0.25)
    a, b = FaultInjector(policy), FaultInjector(policy)
    sched_a, sched_b = [], []
    for r in range(64):
        a.begin_round(r)
        b.begin_round(r)
        a.drop_binding()  # a consumes the binding stream faster
        a.drop_binding()
        b.drop_binding()
        sched_a.append(a.solver_fault(0))
        sched_b.append(b.solver_fault(0))
    assert sched_a == sched_b


def test_quiesce_stops_faults():
    inj = FaultInjector(ChaosPolicy(
        seed=0, api_outage_prob=1.0, binding_drop_prob=1.0, solver_fault_prob=1.0,
    ))
    inj.begin_round(0)
    assert inj.outage_active() and inj.drop_binding()
    inj.quiesce()
    inj.begin_round(1)
    assert not inj.outage_active()
    assert not inj.drop_binding()
    assert inj.solver_fault(0) is None


def test_policy_rejects_unknown_fault_kind():
    with pytest.raises(ValueError, match="unknown solver fault kinds"):
        ChaosPolicy(solver_fault_kinds=("segfault",))


# -- degradation ladder ----------------------------------------------------


class _AlwaysFails(FlowSolver):
    def __init__(self, exc):
        self.exc = exc
        self.calls = 0

    def solve(self, problem):
        self.calls += 1
        raise self.exc


def _tiny_cluster(backend, **kw):
    from ksched_tpu.drivers import add_job, build_cluster

    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=2, max_tasks_per_pu=1, backend=backend, **kw
    )
    add_job(sched, jmap, tmap, num_tasks=3)
    return sched


def test_ladder_steps_down_on_failure():
    failing = _AlwaysFails(RuntimeError("did not converge"))
    ladder = DegradingSolver([("broken", failing), ("cpu_ref", ReferenceSolver())])
    sched = _tiny_cluster(ladder)
    with pytest.warns(RuntimeWarning, match="degrading to 'cpu_ref'"):
        n, _ = sched.schedule_all_jobs()
    assert n == 3  # the fallback rung produced the round
    assert failing.calls == 1
    assert ladder.last_rung == 1 and ladder.last_rung_name == "cpu_ref"
    assert ladder.degradations_total == 1


def test_ladder_exhausted_raises_with_all_failures():
    ladder = DegradingSolver([
        ("a", _AlwaysFails(RuntimeError("x"))),
        ("b", _AlwaysFails(OverflowError("y"))),
    ])
    sched = _tiny_cluster(ladder)
    with pytest.warns(RuntimeWarning):
        with pytest.raises(LadderExhausted) as ei:
            sched.schedule_all_jobs()
    assert [name for name, _ in ei.value.failures] == ["a", "b"]


def test_ladder_does_not_absorb_nondegradable_errors():
    ladder = DegradingSolver([
        ("buggy", _AlwaysFails(TypeError("bug"))),
        ("cpu_ref", ReferenceSolver()),
    ])
    sched = _tiny_cluster(ladder)
    with pytest.raises(TypeError):
        sched.schedule_all_jobs()


def test_build_ladder_dedups_configured_rung():
    names = build_degradation_ladder(ReferenceSolver(), "ref").rung_names()
    assert names == ["ref", "jax"]  # no second cpu_ref rung
    lazy = build_degradation_ladder(_AlwaysFails(RuntimeError("x")), "custom")
    assert lazy.rung_names() == ["custom", "jax", "cpu_ref"]


def test_injected_solver_faults_fire_through_ladder():
    inj = FaultInjector(ChaosPolicy(seed=0, solver_fault_prob=1.0,
                                    solver_fault_kinds=("exception",)))
    oracle = ReferenceSolver()
    ladder = DegradingSolver([("primary", oracle), ("cpu_ref", ReferenceSolver())],
                             injector=inj)
    sched = _tiny_cluster(ladder)
    inj.begin_round(0)
    with pytest.warns(RuntimeWarning, match="injected backend exception"):
        n, _ = sched.schedule_all_jobs()
    assert n == 3  # rung 1 (unfaulted) carried the round
    assert inj.counters["solver_exception"] == 1


def test_nan_cost_rejected_by_backends():
    """Satellite hardening: NaN'd cost inputs must be *rejected* by
    EVERY selectable backend (shared solver/base.check_finite_costs),
    not cast into garbage int costs — a rung that 'succeeds' on a
    poisoned cost model would commit nonsense placements instead of
    triggering the degradation ladder."""
    from ksched_tpu.runtime.chaos import poison_costs
    from ksched_tpu.solver.ell_solver import EllSolver
    from ksched_tpu.solver.jax_solver import JaxSolver
    from ksched_tpu.solver.mega_solver import MegaSolver
    from ksched_tpu.solver.placement import PlacementSolver

    sched = _tiny_cluster(ReferenceSolver())
    sched.gm.compute_topology_statistics(sched.gm.sink_node)
    jds = [jd for jd in sched.jobs_to_schedule.values()
           if sched._compute_runnable_tasks_for_job(jd)]
    sched.gm.add_or_update_job_nodes(jds)
    ps = PlacementSolver(sched.gm, ReferenceSolver())
    ps.state.full_build(sched.gm.cm.graph)
    ps.state.set_excess(sched.gm.sink_node.id, sched.gm.sink_node.excess)
    problem = ps.state.problem()
    bad = poison_costs(problem)
    assert bad.cost.dtype.kind == "f" and np.isnan(bad.cost).any()
    for backend in (ReferenceSolver(), JaxSolver(), EllSolver(), MegaSolver()):
        with pytest.raises(ValueError, match="non-finite arc costs"):
            backend.solve(bad)
    # the clean problem still solves (the check has no false positives)
    assert ReferenceSolver().solve(problem).flow.sum() >= 0


# -- service NOOP round + loop hardening -----------------------------------


def _service(api=None, **kw):
    api = api or SyntheticClusterAPI()
    svc = SchedulerService(api, max_tasks_per_pu=1, **kw)
    svc.init_topology(fake_machines=2, pus_per_core=2)
    return api, svc


def test_noop_round_keeps_previous_assignments():
    """When every rung fails, the round is a NOOP: previous placements
    survive untouched, nothing crashes, and the next (healthy) round
    schedules the backlog."""
    inj = FaultInjector(ChaosPolicy(seed=0, solver_fault_kinds=("nonconverge",)))
    api, svc = _service(injector=inj, tracer=RoundTracer())
    svc.run_round([PodEvent(pod_id="p0"), PodEvent(pod_id="p1")])
    before = dict(svc.scheduler.task_bindings)
    assert len(before) == 2 and len(api.bindings()) == 2

    # force an all-rungs outage for one round
    inj._solver_plan = {0: "nonconverge"}
    inj._solver_plan_all = True
    with pytest.warns(RuntimeWarning, match="NOOP round"):
        bound = svc.run_round([PodEvent(pod_id="p2"), PodEvent(pod_id="p3")])
    assert bound == 0
    assert svc.noop_rounds == 1
    assert svc.backlog_dirty  # the kept backlog flags the next idle poll
    assert dict(svc.scheduler.task_bindings) == before  # assignments kept
    rec = svc.tracer.records[-1]
    assert rec.noop_round and rec.solver_rung == -1
    assert rec.faults_injected.get("solver_nonconverge", 0) >= 1

    # ladder heals next round: backlog (p2, p3) schedules
    inj._solver_plan = {}
    inj._solver_plan_all = False
    bound = svc.run_round([])
    assert bound == 2
    assert len(svc.scheduler.task_bindings) == 4
    assert not svc.backlog_dirty  # a clean full solve clears the flag


def test_run_survives_transient_outage_and_exits_on_close():
    """Satellite regression: an empty batch with the channel OPEN (a
    transient API-server outage longer than the batch timeout) must not
    exit the scheduler; close() must."""
    api, svc = _service()
    done = threading.Event()

    def drive():
        svc.run(pod_batch_timeout_s=0.05, max_rounds=1)
        done.set()

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    # several batch-timeout windows of silence: the loop must idle, not exit
    time.sleep(0.3)
    assert not done.is_set(), "scheduler exited on a transient empty batch"
    api.submit_pod(PodEvent(pod_id="late_pod"))
    t.join(timeout=5)
    assert done.is_set()
    assert len(api.bindings()) == 1  # the late pod was scheduled

    # and with the channel CLOSED, run() exits promptly without a pod
    api2, svc2 = _service()
    api2.close()
    t0 = time.monotonic()
    svc2.run(pod_batch_timeout_s=0.05, max_rounds=5)
    assert time.monotonic() - t0 < 2.0


def test_idle_polls_are_sweep_only_until_backlog_dirty():
    """Regression: a quiet-but-open channel must not cost a full graph
    rebuild + MCMF solve per batch timeout — idle polls run only the
    heartbeat sweep while the backlog is clean; the solver runs again
    when a real batch (or dirty backlog) arrives."""
    api, svc = _service()
    solves = []
    orig = svc.run_once

    def counting(pods):
        solves.append(len(pods))
        return orig(pods)

    svc.run_once = counting
    t = threading.Thread(
        target=svc.run,
        kwargs=dict(pod_batch_timeout_s=0.02, max_rounds=1),
        daemon=True,
    )
    t.start()
    time.sleep(0.3)  # many idle polls' worth of silence
    assert solves == []  # sweep-only: no solver work while quiet
    api.submit_pod(PodEvent(pod_id="p0"))
    t.join(timeout=5)
    assert solves == [1]  # the real batch solved exactly once
    assert len(api.bindings()) == 1


def test_run_advances_injector_rounds_on_idle_polls():
    """Regression: idle (empty-batch) iterations must advance the fault
    injector's round clock — a stale index would re-roll the same
    round's draws on every poll and freeze outage countdowns for the
    whole outage they are meant to time out."""
    inj = FaultInjector(ChaosPolicy(seed=0))
    api, svc = _service(injector=inj)
    t = threading.Thread(
        target=svc.run,
        kwargs=dict(pod_batch_timeout_s=0.02, max_rounds=1),
        daemon=True,
    )
    t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and inj.round_index < 3:
        time.sleep(0.02)
    assert inj.round_index >= 3  # each idle poll consumed one round
    api.submit_pod(PodEvent(pod_id="p0"))
    t.join(timeout=5)
    assert not t.is_alive()


def test_cluster_api_default_poll_pair_agrees_on_close():
    """Regression: a minimal ClusterAPI subclass overriding neither
    poll_pod_batch nor is_closed keeps the blocking contract's
    empty==closed — otherwise run() would busy-spin forever on instant
    empty batches after close."""
    from ksched_tpu.cluster.api import ClusterAPI

    class Minimal(ClusterAPI):
        def get_pod_batch(self, timeout_s):
            return []  # blocking contract: [] only on close

        def get_node_batch(self, timeout_s):
            return []

        def assign_bindings(self, bindings):
            pass

    api = Minimal()
    assert not api.is_closed()  # open until a poll says otherwise
    assert api.poll_pod_batch(0.01) == []
    assert api.is_closed()  # the default pair agrees: the loop exits


def test_dropped_binding_resurfaces_and_reposts():
    """A dropped binding POST re-surfaces the pod; the service re-posts
    on a later round and the binding eventually lands."""
    inj = FaultInjector(ChaosPolicy(seed=0, binding_drop_prob=1.0))
    chaos = ChaosClusterAPI(SyntheticClusterAPI(), inj)
    _, svc = _service(api=chaos, injector=inj, tracer=RoundTracer())
    svc.run_round([PodEvent(pod_id="p0")])
    assert chaos.bindings() == {}  # POST dropped
    assert inj.counters["binding_drop"] == 1
    # scheduler-side the task IS placed; the re-post must not re-place
    assert len(svc.scheduler.task_bindings) == 1

    inj.quiesce()  # next POST goes through
    pods = chaos.poll_pod_batch(0.01)
    assert [p.pod_id for p in pods] == ["p0"]  # re-surfaced
    svc.run_round(pods)
    assert len(chaos.bindings()) == 1
    assert len(svc.scheduler.task_bindings) == 1  # still exactly one task


def test_outage_holds_events_for_later_delivery():
    inj = FaultInjector(ChaosPolicy(seed=0))
    chaos = ChaosClusterAPI(SyntheticClusterAPI(), inj)
    chaos.submit_pod(PodEvent(pod_id="p0"))
    inj._outage_rounds_left = 2
    assert chaos.poll_pod_batch(0.01) == []  # suppressed, not dropped
    assert inj.counters["api_outage_round"] == 1
    inj._outage_rounds_left = 0
    assert [p.pod_id for p in chaos.poll_pod_batch(0.05)] == ["p0"]


# -- the short chaos soak (the CI smoke, in-process) -----------------------


@pytest.mark.parametrize("seed", [7])
def test_chaos_soak_deterministic_with_fault_accounting(seed):
    """A short fixed-seed chaos soak: zero crashes, invariants clean,
    every injected fault accounted for in RoundRecord counters (the
    accounting assert lives inside run_chaos_soak), and final
    placements identical across two runs with the same seed."""
    import argparse

    from tools.soak import run_chaos_soak

    args = argparse.Namespace(
        rounds=48, machines=4, slots=4, seed=seed, chunk=24,
        chaos_backend="ref", chaos_restore_every=20,
    )
    a = run_chaos_soak(args, log=lambda *a, **k: None)
    b = run_chaos_soak(args, log=lambda *a, **k: None)
    assert a["placements"] == b["placements"]
    assert a["all_bindings"] == b["all_bindings"]
    assert a["fault_totals"] == b["fault_totals"]
    assert a["restores"] >= 1  # mid-soak kill-and-restore actually ran
    assert sum(a["fault_totals"].values()) > 0  # chaos actually happened
