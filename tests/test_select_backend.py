"""solver/select.py edge paths: unknown names, fallback semantics, and
the class each registered name resolves to (ISSUE 3 satellite)."""

import warnings

import pytest

import ksched_tpu.solver.native as native_mod
from ksched_tpu.solver.select import make_backend


class _ExplodingNativeSolver:
    def __init__(self, *a, **kw):
        raise RuntimeError("no C++ toolchain in this test")


@pytest.fixture()
def broken_native(monkeypatch):
    monkeypatch.setattr(native_mod, "NativeSolver", _ExplodingNativeSolver)


def test_unknown_backend_raises_value_error():
    with pytest.raises(ValueError, match="unknown backend 'bogus'"):
        make_backend("bogus")


def test_native_fallback_false_reraises(broken_native):
    with pytest.raises(RuntimeError, match="no C\\+\\+ toolchain"):
        make_backend("native", fallback=False)


def test_native_fallback_warns_and_degrades_to_jax(broken_native):
    from ksched_tpu.solver.jax_solver import JaxSolver

    with pytest.warns(RuntimeWarning, match="native backend unavailable"):
        solver = make_backend("native", fallback=True)
    assert isinstance(solver, JaxSolver)


def test_ref_returns_reference_solver():
    from ksched_tpu.solver.cpu_ref import ReferenceSolver

    assert isinstance(make_backend("ref"), ReferenceSolver)


def test_layered_returns_layered_solver():
    from ksched_tpu.solver.layered import LayeredTransportSolver

    assert isinstance(make_backend("layered"), LayeredTransportSolver)


def test_jax_and_ell_and_mega_resolve():
    from ksched_tpu.solver.ell_solver import EllSolver
    from ksched_tpu.solver.jax_solver import JaxSolver
    from ksched_tpu.solver.mega_solver import MegaSolver

    assert isinstance(make_backend("jax"), JaxSolver)
    assert isinstance(make_backend("ell"), EllSolver)
    mega = make_backend("mega")
    assert isinstance(mega, MegaSolver)
    # --backend mega stays total: oversized graphs delegate to a CSR fallback
    assert isinstance(mega.fallback, JaxSolver)


class _WorkingNativeSolver:
    def __init__(self, *a, **kw):
        pass


def test_working_native_emits_no_warning(monkeypatch):
    """When the native build succeeds, the native path must hand back
    the solver without the fallback warning; direct backends likewise."""
    monkeypatch.setattr(native_mod, "NativeSolver", _WorkingNativeSolver)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        solver = make_backend("native")
        make_backend("jax")
        make_backend("ref")
    assert isinstance(solver, _WorkingNativeSolver)
