"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware; the driver separately dry-runs the
multi-chip path (see __graft_entry__.py).
"""

import os

# Force CPU regardless of ambient JAX_PLATFORMS (the machine may expose a
# real TPU via an axon tunnel; tests must not depend on it).
os.environ["JAX_PLATFORMS"] = "cpu"
# Solver-interior telemetry defaults OFF under the tier-1 wall: with it
# on, every solver test would compile the (larger) telemetry variant of
# its executable, and the suite's compile budget is the binding
# constraint. Telemetry behavior is exercised by tests/test_soltel.py
# (explicit per-solver caps, which ignore this default) and the
# chaos/obs smokes run with it ON outside the wall (`make obs-smoke`).
os.environ.setdefault("KSCHED_SOLTEL", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Deregister the tunneled-TPU backend before any backend materializes so
# tests are hermetic even when the tunnel is down (see utils/platform.py).
from ksched_tpu.utils import force_cpu_platform  # noqa: E402

force_cpu_platform()

import pytest  # noqa: E402

from ksched_tpu.utils import seed_rng  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy tests excluded from the budgeted tier-1 "
        "selection (-m 'not slow'); run them with a plain `pytest tests/`",
    )


@pytest.fixture(autouse=True)
def _seeded_rng():
    seed_rng(42)
    yield
