"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware; the driver separately dry-runs the
multi-chip path (see __graft_entry__.py).
"""

import os

# Force CPU regardless of ambient JAX_PLATFORMS (the machine may expose a
# real TPU via an axon tunnel; tests must not depend on it).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The container's sitecustomize registers a tunneled TPU PJRT plugin at
# interpreter boot; if the tunnel is down, merely *initializing* that
# backend hangs — even under JAX_PLATFORMS=cpu. Deregister it before any
# backend is materialized so tests are hermetic.
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# jax may already have been imported by a pytest plugin before this
# conftest ran, capturing the ambient JAX_PLATFORMS; override directly.
jax.config.update("jax_platforms", "cpu")
for _plat in list(getattr(_xb, "_backend_factories", {})):
    if _plat != "cpu":
        _xb._backend_factories.pop(_plat, None)

import pytest  # noqa: E402

from ksched_tpu.utils import seed_rng  # noqa: E402


@pytest.fixture(autouse=True)
def _seeded_rng():
    seed_rng(42)
    yield
