"""Hierarchical equivalence-class chains through the graph manager
(_update_equiv_to_equiv_arcs, reference graph_manager.go:939-970): a
cost model routing task -> job-EC -> rack-EC -> machines must schedule
through the two-level aggregation, and stale EC->EC preferences must be
pruned (removeInvalidECPrefArcs, :732-760)."""

from typing import List, Tuple

from ksched_tpu.costmodels import TrivialCostModel
from ksched_tpu.costmodels.base import Cost
from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.graph.flowgraph import NodeType

JOB_EC = 777_001
RACK_EC = 777_002


class TwoLevelECModel(TrivialCostModel):
    """task -> JOB_EC -> RACK_EC -> every machine (the quincy-style
    rack-aggregator shape). Inherits the trivial model's stats
    machinery; only the preference topology differs."""

    def get_task_equiv_classes(self, task_id: int) -> List[int]:
        return [JOB_EC]

    def get_equiv_class_to_equiv_classes_arcs(self, ec: int) -> List[int]:
        return [RACK_EC] if ec == JOB_EC else []

    def equiv_class_to_equiv_class(self, ec1: int, ec2: int) -> Tuple[Cost, int]:
        # ample capacity through the chain; cost 1 per hop
        return 1, 64

    def get_outgoing_equiv_class_pref_arcs(self, ec: int) -> List[int]:
        # only the RACK EC talks to machines
        return list(self._machines) if ec == RACK_EC else []

    def task_to_equiv_class_aggregator(self, task_id: int, ec: int) -> Cost:
        return 2


def test_two_level_ec_chain_schedules_tasks():
    # preemption on: running tasks keep their EC arcs, so the chain
    # stays connected across the round (with preemption off, pinning
    # drops the arcs and the per-round purge removes idle ECs — see
    # test_pinned_round_purges_idle_ecs)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=2, preemption=True,
        cost_model_factory=TwoLevelECModel,
    )
    add_job(sched, jmap, tmap, num_tasks=3)
    n, _ = sched.schedule_all_jobs()
    assert n == 3
    # both EC nodes exist and the chain arc is present
    assert JOB_EC in sched.gm.task_ec_to_node
    assert RACK_EC in sched.gm.task_ec_to_node
    job_node = sched.gm.task_ec_to_node[JOB_EC]
    rack_node = sched.gm.task_ec_to_node[RACK_EC]
    chain = sched.gm.cm.graph.get_arc(job_node, rack_node)
    assert chain is not None and chain.cost == 1 and chain.cap_upper == 64
    # machines hang off the RACK EC only
    rack_out = {a.dst_node.type for a in rack_node.outgoing.values()}
    assert NodeType.MACHINE in rack_out
    assert all(
        a.dst_node.type != NodeType.MACHINE for a in job_node.outgoing.values()
    )
    # supply invariant after routing through the chain
    assert sched.gm.sink_node.excess == -len(sched.gm.task_to_node)


def test_stale_ec_chain_is_pruned():
    """Dropping the EC->EC preference must delete the chain arc on the
    next round (removeInvalidECPrefArcs parity)."""

    class RetractableModel(TwoLevelECModel):
        chain_on = True

        def get_equiv_class_to_equiv_classes_arcs(self, ec: int) -> List[int]:
            return [RACK_EC] if (ec == JOB_EC and self.chain_on) else []

        def get_outgoing_equiv_class_pref_arcs(self, ec: int) -> List[int]:
            if ec == RACK_EC:
                return list(self._machines)
            if ec == JOB_EC and not self.chain_on:
                return list(self._machines)  # fall back to direct fan-out
            return []

    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=2, preemption=True,
        cost_model_factory=RetractableModel,
    )
    add_job(sched, jmap, tmap, num_tasks=1)
    sched.schedule_all_jobs()
    job_node = sched.gm.task_ec_to_node[JOB_EC]
    rack_node = sched.gm.task_ec_to_node[RACK_EC]
    assert sched.gm.cm.graph.get_arc(job_node, rack_node) is not None

    sched.cost_model.chain_on = False
    add_job(sched, jmap, tmap, num_tasks=1)  # forces a graph update pass
    sched.schedule_all_jobs()
    assert sched.gm.cm.graph.get_arc(job_node, rack_node) is None


def test_pinned_round_purges_idle_ecs_with_debounce():
    """With preemption OFF, placed tasks are pinned (their EC arcs
    deleted), leaving the chain ECs unconnected. The purge is
    debounced: one round of being unconnected marks them, a second
    purge removes them — transiently idle aggregators don't churn, and
    persistently idle ones don't accumulate. The cascade (RACK_EC
    orphaned by JOB_EC's removal) resolves in the same call."""
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=2,
        cost_model_factory=TwoLevelECModel,
    )
    add_job(sched, jmap, tmap, num_tasks=3)
    n, _ = sched.schedule_all_jobs()
    assert n == 3
    # everyone pinned; the round's purge only MARKED the idle ECs
    assert JOB_EC in sched.gm.task_ec_to_node
    sched.gm.purge_unconnected_equiv_class_nodes()  # second observation
    assert not sched.gm.task_ec_to_node  # JOB_EC purged, RACK_EC cascaded
    assert sched.gm.sink_node.excess == -len(sched.gm.task_to_node)
