"""Multi-tenant scheduler-as-a-service: bit-parity + isolation suite.

The acceptance bar (ISSUE 12): every lane of a stacked batched solve
must be BIT-IDENTICAL to the same tenant solved alone — flows,
supersteps, and soltel telemetry rows — across shape buckets, lane
counts, warm/fresh rounds, and a lane whose journal churns endpoints
while its neighbors' journals are cost-only. On top of the solver
parity, the service-level suite asserts end-to-end placement parity
(multi-tenant cell == isolated single-cell process), zero cross-tenant
interference under chaos, per-tenant accounting, admission control,
fairness rotation, and quarantine.
"""

import warnings

import numpy as np
import pytest

from ksched_tpu.graph.device_export import FlowProblem, pad_problem
from ksched_tpu.obs.metrics import Registry
from ksched_tpu.solver.jax_solver import JaxSolver, pad_lane_count
from ksched_tpu.tenancy import (
    AdmissionError,
    AdmissionPolicy,
    LaneSolver,
    MultiTenantService,
    StackedBatcher,
    TenantManager,
)

# ---------------------------------------------------------------------------
# toy per-tenant flow problems (feasible by construction)
# ---------------------------------------------------------------------------

#: three pow2 shape buckets: (n_cap, m_cap, tasks, machines)
BUCKETS = [(32, 64, 6, 8), (64, 128, 14, 12), (128, 256, 30, 20)]


class ToyCell:
    """A tenant's mutable toy graph: tasks -> machines -> sink, churned
    per round either by cost (journal leaves endpoints alone) or by
    endpoint re-wiring (the journal kind that forbids carried flow)."""

    def __init__(self, seed: int, n_cap: int, m_cap: int, tasks: int, machines: int):
        self.rng = np.random.default_rng(seed)
        self.n_cap, self.m_cap = n_cap, m_cap
        self.tasks, self.machines = tasks, machines
        n_real = 2 + tasks + machines
        assert n_real <= n_cap
        self.excess = np.zeros(n_cap, np.int64)
        self.excess[1 : 1 + tasks] = 1
        self.sink = 1 + tasks + machines
        self.excess[self.sink] = -tasks
        src, dst, cap, cost = [], [], [], []
        self.m0 = 1 + tasks  # first machine node
        for t in range(1, 1 + tasks):
            for mm in self.rng.choice(machines, 3, replace=False):
                src.append(t)
                dst.append(self.m0 + int(mm))
                cap.append(1)
                cost.append(int(self.rng.integers(1, 50)))
        for mm in range(machines):
            src.append(self.m0 + mm)
            dst.append(self.sink)
            cap.append(tasks)
            cost.append(1)
        k = len(src)
        assert k <= m_cap
        self.src = np.zeros(m_cap, np.int32)
        self.dst = np.zeros(m_cap, np.int32)
        self.cap = np.zeros(m_cap, np.int32)
        self.cost = np.zeros(m_cap, np.int32)
        self.src[:k], self.dst[:k] = src, dst
        self.cap[:k], self.cost[:k] = cap, cost
        self.k = k
        self.task_arcs = tasks * 3  # arcs eligible for churn

    def churn(self, kind: str) -> None:
        idx = self.rng.choice(self.task_arcs, 2, replace=False)
        if kind == "cost":
            for i in idx:
                self.cost[i] = int(self.rng.integers(1, 50))
        elif kind == "endpoint":
            for i in idx:
                self.dst[i] = self.m0 + int(self.rng.integers(0, self.machines))
        else:  # pragma: no cover
            raise ValueError(kind)

    def problem(self) -> FlowProblem:
        return FlowProblem(
            num_nodes=self.n_cap,
            excess=self.excess.copy(),
            node_type=np.zeros(self.n_cap, np.int8),
            src=self.src.copy(),
            dst=self.dst.copy(),
            cap=self.cap.copy(),
            cost=self.cost.copy(),
            flow_offset=np.zeros(self.m_cap, np.int32),
            num_arcs=self.k,
        )


def _tel_rows(solver):
    tel = solver.last_telemetry
    return None if tel is None else np.asarray(tel.rows)


# ---------------------------------------------------------------------------
# stacked-solve bit-parity: lanes vs the tenant solved alone
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("lanes", [2, 4, 16])
def test_stacked_lanes_bit_identical_to_isolated(lanes):
    """The acceptance check, exhaustively: 2/4/16 tenants spread over
    3 shape buckets, driven through a churn script in which lane 0's
    journal RE-WIRES ENDPOINTS every round (the journal-scoped fresh-
    restart path) while every other lane's journal is cost-only (the
    warm refit path). Each lane's flow, superstep count, warm scope,
    and soltel telemetry rows must be bit-identical to the same tenant
    solved alone by the plain JaxSolver with the same policy."""
    cells = [
        ToyCell(100 + i, *BUCKETS[i % len(BUCKETS)]) for i in range(lanes)
    ]
    batcher = StackedBatcher()
    lane_solvers = [
        LaneSolver(batcher, tenant=f"t{i}", restart_budget=64, telemetry=8)
        for i in range(lanes)
    ]
    iso_cells = [
        ToyCell(100 + i, *BUCKETS[i % len(BUCKETS)]) for i in range(lanes)
    ]
    iso_solvers = [
        JaxSolver(slot_stable=False, restart_budget=64, telemetry=8)
        for _ in range(lanes)
    ]
    for r in range(4):
        if r > 0:
            for group in (cells, iso_cells):
                for i, c in enumerate(group):
                    c.churn("endpoint" if i == 0 else "cost")
        # multi-tenant: dispatch every lane, ONE flush, then complete
        pendings = [
            ls.solve_async(c.problem()) for ls, c in zip(lane_solvers, cells)
        ]
        batcher.flush()
        results = [ls.complete(p) for ls, p in zip(lane_solvers, pendings)]
        for i in range(lanes):
            iso = iso_solvers[i].solve(iso_cells[i].problem())
            got = results[i]
            assert np.array_equal(got.flow, iso.flow), (r, i)
            assert got.objective == iso.objective, (r, i)
            assert lane_solvers[i].last_supersteps == iso_solvers[i].last_supersteps, (r, i)
            assert lane_solvers[i].last_warm_scope == iso_solvers[i].last_warm_scope, (r, i)
            lane_rows, iso_rows = _tel_rows(lane_solvers[i]), _tel_rows(iso_solvers[i])
            assert (lane_rows is None) == (iso_rows is None)
            if lane_rows is not None:
                assert np.array_equal(lane_rows, iso_rows), (r, i)
        if r > 0:
            # the churn script exercised BOTH warm scopes this round
            scopes = {ls.last_warm_scope for ls in lane_solvers}
            assert lane_solvers[0].last_warm_scope == "fresh"
            assert "warm" in scopes


def test_stacked_one_program_per_bucket_policy():
    """Same-bucket same-policy lanes ride ONE compiled call: the flush
    dispatches exactly as many programs as there are (bucket, policy)
    groups, not one per tenant."""
    cells = [ToyCell(7 + i, *BUCKETS[0]) for i in range(5)]
    batcher = StackedBatcher()
    solvers = [LaneSolver(batcher, tenant=f"t{i}") for i in range(5)]
    pendings = [s.solve_async(c.problem()) for s, c in zip(solvers, cells)]
    assert batcher.flush() == 1  # one bucket, one policy -> one program
    for s, p in zip(solvers, pendings):
        s.complete(p)
    # two buckets -> two programs
    cells2 = [ToyCell(50, *BUCKETS[0]), ToyCell(51, *BUCKETS[1])]
    solvers2 = [LaneSolver(batcher, tenant=f"u{i}") for i in range(2)]
    pend2 = [s.solve_async(c.problem()) for s, c in zip(solvers2, cells2)]
    assert batcher.flush() == 2
    for s, p in zip(solvers2, pend2):
        s.complete(p)


def test_quarantined_lane_solves_in_its_own_group():
    cells = [ToyCell(60 + i, *BUCKETS[0]) for i in range(3)]
    batcher = StackedBatcher()
    solvers = [LaneSolver(batcher, tenant=f"t{i}") for i in range(3)]
    solvers[1].quarantined = True
    pendings = [s.solve_async(c.problem()) for s, c in zip(solvers, cells)]
    assert batcher.flush() == 2  # shared group + the solo lane
    flows = [s.complete(p).flow for s, p in zip(solvers, pendings)]
    # quarantine must not change the answer, only the grouping
    iso = JaxSolver(slot_stable=False)
    assert np.array_equal(flows[1], iso.solve(cells[1].problem()).flow)


def test_restart_escape_parity_with_isolated():
    """A lane whose warm attempt blows a tiny restart budget escalates
    per-lane (fresh restart, then cost-scaling) and must still match
    the isolated JaxSolver with the same budget, attempt for attempt."""
    cell = ToyCell(77, *BUCKETS[0])
    iso_cell = ToyCell(77, *BUCKETS[0])
    batcher = StackedBatcher()
    lane = LaneSolver(batcher, tenant="t0", restart_budget=1)
    iso = JaxSolver(slot_stable=False, restart_budget=1)
    for r in range(3):
        if r:
            cell.churn("cost")
            iso_cell.churn("cost")
        got = lane.solve(cell.problem())
        want = iso.solve(iso_cell.problem())
        assert np.array_equal(got.flow, want.flow), r
        assert lane.last_supersteps == iso.last_supersteps, r


def test_lane_bucket_floor_pads_and_matches_isolated_padding():
    """bucket_floor pads a small tenant up into a shared bucket; the
    result must equal the plain JaxSolver solving the identically
    padded problem (bucket choice is a per-tenant property — the
    docstring's parity caveat)."""
    cell = ToyCell(5, *BUCKETS[0])
    batcher = StackedBatcher()
    lane = LaneSolver(batcher, tenant="t0", bucket_floor=(64, 128))
    got = lane.solve(cell.problem())
    iso = JaxSolver(slot_stable=False)
    padded = pad_problem(cell.problem(), 64, 128)
    want = iso.solve(padded)
    assert np.array_equal(got.flow, want.flow[: cell.m_cap])
    assert got.objective == want.objective


def test_pad_problem_rejects_shrink_and_is_inert():
    p = ToyCell(3, *BUCKETS[0]).problem()
    with pytest.raises(ValueError):
        pad_problem(p, 16, 16)
    q = pad_problem(p, 64, 128)
    assert q.num_nodes == 64 and len(q.src) == 128
    assert (q.cap[p.cap.shape[0]:] == 0).all()
    assert q.num_arcs == p.num_arcs
    assert pad_problem(p, p.num_nodes, len(p.src)) is p


def test_pad_lane_count():
    assert [pad_lane_count(k) for k in (1, 2, 3, 4, 5, 9, 16)] == [
        1, 2, 4, 4, 8, 16, 16,
    ]


def test_flush_group_failure_degrades_only_that_group():
    """Per-GROUP fault barrier in the batcher: a stacked-dispatch
    failure marks only its own group's lanes failed (their complete()
    raises a DEGRADABLE RuntimeError — the tenant ladder's cue), other
    groups still solve, and the batcher stays usable next round."""
    cells = [ToyCell(80, *BUCKETS[0]), ToyCell(81, *BUCKETS[1])]
    batcher = StackedBatcher()
    solvers = [LaneSolver(batcher, tenant=f"t{i}") for i in range(2)]
    pendings = [s.solve_async(c.problem()) for s, c in zip(solvers, cells)]
    # sabotage ONE group's dispatch (the smaller bucket's lane 0)
    orig = batcher._flush_group

    def flaky(key, reqs, jnp):
        if key[0] == BUCKETS[0][0]:
            raise RuntimeError("injected compile failure")
        return orig(key, reqs, jnp)

    batcher._flush_group = flaky
    batcher.flush()
    batcher._flush_group = orig
    with pytest.raises(RuntimeError, match="stacked batch dispatch failed"):
        solvers[0].complete(pendings[0])
    # the OTHER group solved normally
    res = solvers[1].complete(pendings[1])
    iso = JaxSolver(slot_stable=False)
    assert np.array_equal(res.flow, iso.solve(cells[1].problem()).flow)
    # the batcher is not poisoned: the failed tenant's next round works
    again = solvers[0].solve(cells[0].problem())
    iso0 = JaxSolver(slot_stable=False)
    assert np.array_equal(again.flow, iso0.solve(cells[0].problem()).flow)


def test_empty_lane_matches_jax_solver_contract():
    """A problem with no arcs short-circuits exactly like JaxSolver."""
    p = FlowProblem(
        num_nodes=16,
        excess=np.zeros(16, np.int64),
        node_type=np.zeros(16, np.int8),
        src=np.zeros(0, np.int32),
        dst=np.zeros(0, np.int32),
        cap=np.zeros(0, np.int32),
        cost=np.zeros(0, np.int32),
        flow_offset=np.zeros(0, np.int32),
        num_arcs=0,
    )
    lane = LaneSolver(StackedBatcher(), tenant="t0")
    res = lane.solve(p)
    assert res.objective == 0 and len(res.flow) == 0


# ---------------------------------------------------------------------------
# manager: admission, fairness, quarantine
# ---------------------------------------------------------------------------


class _FakeLane:
    quarantined = False


def test_admission_caps():
    mgr = TenantManager(AdmissionPolicy(max_tenants=2, max_nodes=1 << 10, max_arcs=1 << 12))
    mgr.admit("a", 100, 200)
    with pytest.raises(AdmissionError):
        mgr.admit("a", 100, 200)  # duplicate
    with pytest.raises(AdmissionError):
        mgr.admit("big", 1 << 11, 100)  # size cap
    mgr.admit("b", 100, 200)
    with pytest.raises(AdmissionError):
        mgr.admit("c", 100, 200)  # max_tenants
    mgr.evict("b")
    mgr.admit("c", 100, 200)


def test_admission_bucket_lane_cap():
    mgr = TenantManager(AdmissionPolicy(max_lanes_per_bucket=2))
    mgr.admit("a", 100, 200)
    mgr.admit("b", 100, 200)
    with pytest.raises(AdmissionError):
        mgr.admit("c", 100, 200)  # same pow2 bucket, full
    mgr.admit("d", 1000, 2000)  # different bucket still admits


def test_fairness_rotation():
    mgr = TenantManager()
    for t in ("a", "b", "c"):
        mgr.admit(t, 10, 10)
    assert mgr.order(0) == ["a", "b", "c"]
    assert mgr.order(1) == ["b", "c", "a"]
    assert mgr.order(2) == ["c", "a", "b"]
    assert mgr.order(3) == ["a", "b", "c"]


def test_quarantine_after_streak_and_release():
    policy = AdmissionPolicy(quarantine_after=2, quarantine_rounds=3)
    mgr = TenantManager(policy)
    lane = _FakeLane()
    mgr.admit("a", 10, 10)
    mgr.register_lane("a", lane)
    mgr.note_round("a", warm_escape=True)
    assert not lane.quarantined
    mgr.note_round("a", warm_escape=True)  # streak hits 2 -> quarantine
    assert lane.quarantined
    for _ in range(3):
        mgr.note_round("a")
    assert not lane.quarantined  # window served, released
    # clean rounds reset the streak
    mgr.note_round("a", noop=True)
    mgr.note_round("a")
    mgr.note_round("a", noop=True)
    assert not lane.quarantined


# ---------------------------------------------------------------------------
# service: end-to-end isolation, chaos containment, accounting
# ---------------------------------------------------------------------------


def _drive_cells(tenant_ids, chaos_on=None, rounds=5, registry=None):
    from ksched_tpu.cluster import PodEvent
    from ksched_tpu.runtime.chaos import ChaosPolicy, FaultInjector

    reg = registry if registry is not None else Registry()
    mts = MultiTenantService(registry=reg, pipeline=True)
    cells = {}
    for tid in tenant_ids:
        inj = None
        if tid == chaos_on:
            inj = FaultInjector(
                ChaosPolicy(
                    seed=3, solver_fault_prob=0.5, solver_total_outage_prob=0.3
                )
            )
        cells[tid] = mts.add_tenant(
            tid, machines=3, pus_per_core=2, slots=4,
            seed=sum(map(ord, tid)), injector=inj,
        )
    rngs = {tid: np.random.default_rng(sum(map(ord, tid))) for tid in tenant_ids}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for r in range(rounds):
            for tid, cell in cells.items():
                for j in range(int(rngs[tid].integers(0, 3))):
                    cell.api.submit_pod(PodEvent(pod_id=f"{tid}_pod_{r}_{j}"))
            mts.run_round(now=float(r))
        mts.drain()
    out = {}
    for tid in tenant_ids:
        recs = cells[tid].svc.tracer.records
        out[tid] = dict(
            bindings=dict(cells[tid].api.bindings()),
            work=[rec.solver_work for rec in recs],
            scheduled=[rec.num_scheduled for rec in recs],
            faults=sum(sum(r.faults_injected.values()) for r in recs),
            degr=sum(r.degradations for r in recs),
            noops=sum(1 for r in recs if r.noop_round),
            tenants={r.tenant for r in recs},
        )
    return out, mts


def test_service_isolated_parity():
    """Each cell of a 3-tenant process must schedule bit-identically to
    the same cell running as the only tenant of its own process."""
    multi, _ = _drive_cells(["a", "b", "c"])
    for tid in ("a", "b", "c"):
        solo, _ = _drive_cells([tid])
        for key in ("bindings", "work", "scheduled"):
            assert solo[tid][key] == multi[tid][key], (tid, key)
        assert multi[tid]["tenants"] == {tid}


def test_service_chaos_zero_cross_tenant_interference():
    """Chaos on tenant a: its lane degrades/NOOPs; every other cell's
    records carry ZERO faults/degradations/noops and its placements
    stay bit-identical to the isolated run."""
    multi, _ = _drive_cells(["a", "b", "c"], chaos_on="a", rounds=8)
    assert multi["a"]["faults"] > 0 and multi["a"]["degr"] > 0
    for tid in ("b", "c"):
        assert multi[tid]["faults"] == 0
        assert multi[tid]["degr"] == 0
        assert multi[tid]["noops"] == 0
        solo, _ = _drive_cells([tid], rounds=8)
        assert solo[tid]["bindings"] == multi[tid]["bindings"]
        assert solo[tid]["work"] == multi[tid]["work"]


def test_service_per_tenant_registry_accounting():
    """One shared parent registry, per-tenant label: rounds land under
    each cell's tenant label and never bleed across."""
    reg = Registry()
    out, mts = _drive_cells(["a", "b"], rounds=4, registry=reg)
    for tid in ("a", "b"):
        sched = reg.value("ksched_rounds_total", tenant=tid, kind="sched")
        idle = reg.value("ksched_rounds_total", tenant=tid, kind="idle")
        noop = reg.value("ksched_rounds_total", tenant=tid, kind="noop")
        assert sched + idle + noop == len(out[tid]["work"])
    assert reg.value("ksched_tenants") == 2
    assert reg.value("ksched_tenant_batch_flushes_total") > 0


def test_service_device_resident_cells_match_host_cells():
    """Per-tenant DeviceResidentState: cells whose lanes consume the
    persistent device buffers (delta-sized h2d per tenant) must place
    bit-identically to host-array cells."""
    from ksched_tpu.cluster import PodEvent

    def drive(resident):
        mts = MultiTenantService(
            registry=Registry(), pipeline=True, device_resident=resident
        )
        cells = {
            t: mts.add_tenant(
                t, machines=3, pus_per_core=2, slots=4, seed=sum(map(ord, t))
            )
            for t in ("a", "b")
        }
        rngs = {t: np.random.default_rng(sum(map(ord, t))) for t in cells}
        for r in range(5):
            for t, c in cells.items():
                for j in range(int(rngs[t].integers(0, 3))):
                    c.api.submit_pod(PodEvent(pod_id=f"{t}_p{r}_{j}"))
            mts.run_round(now=float(r))
        mts.drain()
        return {
            t: (
                dict(c.api.bindings()),
                [rec.solver_work for rec in c.svc.tracer.records],
            )
            for t, c in cells.items()
        }

    assert drive(False) == drive(True)


def test_no_work_split_rounds_record_as_idle_sweeps():
    """A cell with no runnable work this round must record an IDLE
    sweep (solver_rung -1, excluded from latency percentiles), not a
    solved round with zeroed timings — otherwise a lightly loaded
    tenant's published p50 drags toward zero."""
    from ksched_tpu.cluster import PodEvent

    mts = MultiTenantService(registry=Registry(), pipeline=True)
    cell = mts.add_tenant("a", machines=2, pus_per_core=2, slots=4, seed=1)
    cell.api.submit_pod(PodEvent(pod_id="a_p0"))
    mts.run_round(now=0.0)  # real work
    for r in range(3):  # quiet rounds: nothing runnable
        mts.run_round(now=1.0 + r)
    mts.drain()
    recs = cell.svc.tracer.records
    assert [r.solver_rung for r in recs] == [0, -1, -1, -1]
    s = cell.svc.tracer.summary()
    assert s["rounds"] == 1 and s["idle_rounds"] == 3


def test_service_post_failure_does_not_wedge_the_fleet():
    """Per-cell fault barrier: one tenant's binding-POST failure in its
    dispatch window is warned + retried, every other cell completes,
    and the NEXT round proceeds for all cells (no wedged split-round
    latch)."""
    from ksched_tpu.cluster import PodEvent

    mts = MultiTenantService(registry=Registry(), pipeline=True)
    cells = {
        t: mts.add_tenant(t, machines=2, pus_per_core=2, slots=4, seed=ord(t[0]))
        for t in ("a", "b")
    }
    for t, c in cells.items():
        for j in range(2):
            c.api.submit_pod(PodEvent(pod_id=f"{t}_p{j}"))
    mts.run_round(now=0.0)  # round 0 queues bindings for the window

    fail = {"n": 0}
    real_assign = cells["a"].api.assign_bindings

    def flaky(bindings):
        fail["n"] += 1
        raise OSError("control plane hiccup")

    cells["a"].api.assign_bindings = flaky
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        mts.run_round(now=1.0)  # a's POST fails inside the window
    assert fail["n"] == 1
    assert any("queued for retry" in str(w.message) for w in caught)
    cells["a"].api.assign_bindings = real_assign
    # the fleet is not wedged: both cells run the next round, and a's
    # restored batch flushes
    mts.run_round(now=2.0)
    mts.drain()
    assert len(cells["a"].api.bindings()) == 2
    assert len(cells["b"].api.bindings()) == 2


def test_service_admission_error_rolls_back():
    mts = MultiTenantService(
        registry=Registry(),
        policy=AdmissionPolicy(max_tenants=1),
    )
    mts.add_tenant("a", machines=2, slots=2)
    with pytest.raises(AdmissionError):
        mts.add_tenant("b", machines=2, slots=2)
    assert list(mts.cells) == ["a"]
    mts.remove_tenant("a")
    mts.add_tenant("b", machines=2, slots=2)
    assert list(mts.cells) == ["b"]
