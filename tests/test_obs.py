"""Observability subsystem tests (ksched_tpu/obs).

Covers the exposition-correctness contract from the obs issue:
Prometheus text conformance (label escaping, `_bucket` monotonicity,
`_sum`/`_count` consistency with ingested samples), span nesting and
parenting under exceptions, flight-recorder dump triggers (deadline
miss, NOOP round, crash hook), zero-overhead no-op mode when obs is
disabled, the http_api stats-counter hammer, and live-endpoint
round-trips through a real socket.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from ksched_tpu.obs import (
    FlightRecorder,
    MetricsServer,
    Registry,
    SpanTracer,
    dump_registry,
    parse_prometheus,
    render_prometheus,
    scoped_registry,
    span,
    start_span,
)
from ksched_tpu.obs import metrics as obs_metrics
from ksched_tpu.obs.devprof import (
    ARC_RECORD_BYTES,
    DeviceProfiler,
    delta_nbytes,
    journal_nbytes,
)
from ksched_tpu.obs.metrics import NULL_REGISTRY, log_buckets
from ksched_tpu.runtime.trace import RoundRecord, RoundTracer


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = Registry()
    c = reg.counter("c_total", "a counter")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g", "a gauge")
    g.set(7)
    g.dec(3)
    assert g.value == 4
    h = reg.histogram("h_ms", "a histogram", buckets=(1, 2, 4))
    for v in (0.5, 1.5, 3, 100):
        h.observe(v)
    assert h.count == 4 and h.sum == 105.0


def test_labels_get_or_create_and_mismatch_errors():
    reg = Registry()
    fam = reg.counter("ev_total", "events", labelnames=("kind",))
    fam.labels(kind="a").inc()
    fam.labels("a").inc()  # positional form hits the same child
    assert reg.value("ev_total", kind="a") == 2
    assert reg.value("ev_total", kind="missing") == 0
    # same name again is get-or-create...
    assert reg.counter("ev_total", labelnames=("kind",)) is fam
    # ...but kind/label drift is a hard error
    with pytest.raises(ValueError):
        reg.gauge("ev_total")
    with pytest.raises(ValueError):
        reg.counter("ev_total", labelnames=("other",))
    with pytest.raises(ValueError):
        reg.counter("bad name!")
    # histogram bucket drift is a hard error too (silently landing
    # samples in bounds the caller did not ask for would skew every
    # percentile estimated from them)
    h = reg.histogram("lat2_ms", "latency", buckets=(1, 2, 4))
    assert reg.histogram("lat2_ms", buckets=(1, 2, 4)) is h
    assert reg.histogram("lat2_ms") is h  # unspecified accepts existing
    with pytest.raises(ValueError):
        reg.histogram("lat2_ms", buckets=(1, 2, 8))


def test_log_buckets_cover_range():
    b = log_buckets(1, 64, 2.0)
    assert b == (1, 2, 4, 8, 16, 32, 64)
    with pytest.raises(ValueError):
        log_buckets(0, 10)


# ---------------------------------------------------------------------------
# Prometheus text conformance
# ---------------------------------------------------------------------------


def test_exposition_label_escaping_round_trips():
    reg = Registry()
    fam = reg.counter("esc_total", 'help with \\ and\nnewline', labelnames=("k",))
    nasty = 'a"b\\c\nd'
    fam.labels(k=nasty).inc(3)
    text = render_prometheus(reg)
    assert '\\"' in text and "\\n" in text and "\\\\" in text
    parsed = parse_prometheus(text)
    assert parsed[("esc_total", (("k", nasty),))] == 3


def test_exposition_bucket_monotonicity_and_sum_count():
    reg = Registry()
    h = reg.histogram("lat_ms", "latency", buckets=(1, 10, 100))
    samples = [0.5, 0.5, 5, 50, 500, 7, 1]  # incl. exact bound (le semantics)
    for v in samples:
        h.observe(v)
    parsed = parse_prometheus(render_prometheus(reg))
    buckets = sorted(
        (float("inf") if dict(k[1])["le"] == "+Inf" else float(dict(k[1])["le"]), v)
        for k, v in parsed.items()
        if k[0] == "lat_ms_bucket"
    )
    # cumulative and non-decreasing, +Inf equals _count
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)
    assert counts[-1] == parsed[("lat_ms_count", ())] == len(samples)
    assert parsed[("lat_ms_sum", ())] == pytest.approx(sum(samples))
    # le="1" holds the two 0.5s and the exact 1 (le is inclusive)
    assert buckets[0] == (1.0, 3)


def test_exposition_served_over_http():
    reg = Registry()
    reg.counter("served_total", "x").inc(5)
    srv = MetricsServer(port=0, registry=reg)
    try:
        with urllib.request.urlopen(srv.url + "/metricsz", timeout=5) as r:
            assert "version=0.0.4" in r.headers["Content-Type"]
            text = r.read().decode()
        assert parse_prometheus(text)[("served_total", ())] == 5
        with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(srv.url + "/varz", timeout=5) as r:
            assert json.loads(r.read())["served_total"]["samples"][0]["value"] == 5
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(srv.url + "/nope", timeout=5)
        assert exc.value.code == 404
    finally:
        srv.stop()


def test_registry_snapshot_dump(tmp_path):
    reg = Registry()
    reg.histogram("h_ms", "h", buckets=(1, 2)).observe(1.5)
    path = tmp_path / "snap.json"
    dump_registry(reg, str(path))
    doc = json.loads(path.read_text())
    sample = doc["metrics"]["h_ms"]["samples"][0]
    assert sample["count"] == 1 and sample["buckets"][-1][0] == "+Inf"


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_and_parenting():
    tracer = SpanTracer()
    with tracer:
        with span("outer") as outer:
            with span("inner", k=1):
                pass
            with span("inner2"):
                pass
    events = {e["name"]: e for e in tracer.events()}
    assert events["inner"]["args"]["parent"] == "outer"
    assert events["inner2"]["args"]["parent_sid"] == outer.sid
    assert "parent" not in events["outer"]["args"]
    # time containment (what Perfetto uses for visual nesting)
    assert events["outer"]["ts"] <= events["inner"]["ts"]
    assert (
        events["inner"]["ts"] + events["inner"]["dur"]
        <= events["outer"]["ts"] + events["outer"]["dur"] + 1e-6
    )


def test_span_exception_records_error_and_restores_parent():
    tracer = SpanTracer()
    with tracer:
        with span("root"):
            with pytest.raises(RuntimeError):
                with span("fails"):
                    raise RuntimeError("boom")
            with span("after"):
                pass
    events = {e["name"]: e for e in tracer.events()}
    assert "RuntimeError: boom" in events["fails"]["args"]["error"]
    # the failed span unwound cleanly: the next span parents to root
    assert events["after"]["args"]["parent"] == "root"


def test_unwind_closes_open_manual_spans():
    # the manual-span error path (bulk.py _round_layered): an exception
    # with stats/decode spans still open must close the whole chain so
    # later spans are not mis-parented under a dead span
    import sys

    from ksched_tpu.obs.spans import unwind

    tracer = SpanTracer()
    with tracer:
        outer = start_span("round")
        start_span("decode")  # left open, as a mid-body exception would
        try:
            raise RuntimeError("boom")
        except RuntimeError:
            unwind(outer, *sys.exc_info())
        with span("next_round"):
            pass
    events = {e["name"]: e for e in tracer.events()}
    assert "RuntimeError: boom" in events["decode"]["args"]["error"]
    assert "RuntimeError: boom" in events["round"]["args"]["error"]
    assert "parent" not in events["next_round"]["args"]  # top-level again
    # without a tracer, unwind still closes the outer span for timing
    sp = start_span("untraced_round")
    unwind(sp, None, None, None)
    assert sp.dur_s > 0


def test_span_not_recorded_without_tracer():
    tracer = SpanTracer()
    with span("untraced"):
        pass
    assert tracer.events() == []
    sp = start_span("also_untraced")
    assert sp.finish() >= 0.0  # still times


def test_span_double_close_is_noop():
    tracer = SpanTracer()
    with tracer:
        sp = start_span("once")
        sp.finish()
        d = sp.dur_s
        sp.finish()
        assert sp.dur_s == d
    assert len(tracer.events()) == 1


def test_tracer_ring_and_slicing():
    tracer = SpanTracer(capacity=4)
    with tracer:
        for i in range(3):
            with span(f"s{i}"):
                pass
        mark = tracer.mark()
        for i in range(3):
            with span(f"t{i}"):
                pass
    assert tracer.total == 6 and tracer.dropped == 2
    since = [e["name"] for e in tracer.events_since(mark)]
    assert since == ["t0", "t1", "t2"]
    doc = tracer.chrome_trace()
    assert len(doc["traceEvents"]) == 4  # ring capacity


def test_tracer_install_stacks():
    a, b = SpanTracer(), SpanTracer()
    a.install()
    b.install()
    with span("inner_only"):
        pass
    b.uninstall()
    with span("outer_only"):
        pass
    a.uninstall()
    assert [e["name"] for e in b.events()] == ["inner_only"]
    assert [e["name"] for e in a.events()] == ["outer_only"]


# ---------------------------------------------------------------------------
# no-op mode
# ---------------------------------------------------------------------------


def test_disabled_obs_is_inert():
    assert obs_metrics.enabled()
    obs_metrics.set_enabled(False)
    try:
        reg = obs_metrics.get_registry()
        assert reg is NULL_REGISTRY
        c = reg.counter("anything_total", "x", labelnames=("k",))
        c.labels(k="a").inc(100)
        c.inc()
        c.observe(5)
        assert c.value == 0 and reg.collect() == [] and reg.snapshot() == {}
        assert render_prometheus(reg) == ""
    finally:
        obs_metrics.set_enabled(True)


def test_scoped_registry_swaps_and_restores():
    outer = obs_metrics.get_registry()
    with scoped_registry() as reg:
        assert obs_metrics.get_registry() is reg
        reg.counter("scoped_total", "x").inc()
    assert obs_metrics.get_registry() is outer
    assert reg.value("scoped_total") == 1


def test_scoped_registry_nests():
    with scoped_registry() as outer:
        outer.counter("outer_total", "x").inc()
        with scoped_registry() as inner:
            assert obs_metrics.get_registry() is inner
            inner.counter("inner_total", "x").inc()
        assert obs_metrics.get_registry() is outer
    assert outer.value("outer_total") == 1 and outer.value("inner_total") == 0


def test_scoped_registry_is_thread_confined():
    """The multi-tenant safety property: concurrent scopes in different
    threads must not clobber each other (the old process-global swap
    did), and a scope never leaks into an unscoped thread."""
    base = obs_metrics.get_registry()
    errors = []
    barrier = threading.Barrier(4)

    def run(i):
        try:
            with scoped_registry() as reg:
                barrier.wait(timeout=5)  # every thread inside a scope at once
                assert obs_metrics.get_registry() is reg
                reg.counter("private_total", "x").inc(i + 1)
                barrier.wait(timeout=5)
                assert obs_metrics.get_registry() is reg
                assert reg.value("private_total") == i + 1  # no cross-talk
            assert obs_metrics.get_registry() is base
        except Exception as e:  # noqa: BLE001 — surfaced via the errors list
            errors.append((i, e))
            try:
                barrier.abort()
            except Exception:  # noqa: BLE001
                pass

    threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors
    assert obs_metrics.get_registry() is base


def test_scoped_registry_out_of_order_exit_is_an_error():
    a = scoped_registry()
    b = scoped_registry()
    a.__enter__()
    b.__enter__()
    with pytest.raises(RuntimeError):
        a.__exit__(None, None, None)
    b.__exit__(None, None, None)
    a.__exit__(None, None, None)


def test_registry_scoped_label_views():
    """Registry.scoped(tenant=...) views share ONE parent family with
    the scope label prepended; per-view samples never alias."""
    reg = Registry()
    a = reg.scoped(tenant="a")
    b = reg.scoped(tenant="b")
    a.counter("ksched_rt_total", "x").inc(2)
    b.counter("ksched_rt_total", "x").inc(5)
    assert reg.value("ksched_rt_total", tenant="a") == 2
    assert reg.value("ksched_rt_total", tenant="b") == 5
    assert a.value("ksched_rt_total") == 2
    # labelled families compose: scope labels come first
    fam = a.counter("ksched_rt_kinds_total", "x", labelnames=("kind",))
    fam.labels(kind="noop").inc()
    assert reg.value("ksched_rt_kinds_total", tenant="a", kind="noop") == 1
    assert reg.value("ksched_rt_kinds_total", tenant="b", kind="noop") == 0
    # histograms keep their buckets through the view
    h = b.histogram("ksched_rt_ms", "x", buckets=(1, 2, 4))
    h.observe(3)
    assert b.value("ksched_rt_ms") == 1
    # the text exposition carries the tenant label
    text = render_prometheus(reg)
    assert 'ksched_rt_total{tenant="a"} 2' in text
    # nested scoping accumulates labels
    ab = a.scoped(shard="0")
    ab.counter("ksched_rt_nested_total", "x").inc()
    assert reg.value("ksched_rt_nested_total", tenant="a", shard="0") == 1


def test_registry_scoped_label_collision_is_an_error():
    reg = Registry()
    view = reg.scoped(tenant="a")
    with pytest.raises(ValueError):
        view.counter("ksched_collide_total", "x", labelnames=("tenant",))
    # and a scope-labelled name cannot silently alias an unscoped one
    reg.counter("ksched_plain_total", "x")
    with pytest.raises(ValueError):
        view.counter("ksched_plain_total", "x")


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def _rec(i, **kw):
    rec = RoundRecord(round_index=i, wall_time=0.0, phases_ms={"total": 1.0})
    for k, v in kw.items():
        setattr(rec, k, v)
    return rec


def test_flight_dumps_on_deadline_miss_and_noop(tmp_path):
    reg = Registry()
    fl = FlightRecorder(capacity=4, dump_dir=str(tmp_path), registry=reg,
                        min_rounds_between_dumps=3)
    for i in range(3):
        assert fl.note_round(_rec(i)) is None
    path = fl.note_round(_rec(3, deadline_miss=True), span_events=[{"ph": "X", "name": "round"}])
    assert path is not None
    doc = json.loads(open(path).read())
    assert doc["reason"] == "deadline_miss"
    assert len(doc["rounds"]) == 4  # ring capacity
    assert doc["rounds"][-1]["record"]["deadline_miss"] is True
    assert doc["traceEvents"] == [{"ph": "X", "name": "round"}]
    # a NOOP round is a different trigger kind: dumps immediately
    assert fl.note_round(_rec(4, noop_round=True)) is not None
    # rate limit: another miss right away is suppressed...
    assert fl.note_round(_rec(5, deadline_miss=True)) is None
    # ...but fires again once the window passes
    assert fl.note_round(_rec(6, deadline_miss=True)) is not None
    assert reg.value("ksched_flight_dumps_total", reason="deadline_miss") == 2
    assert reg.value("ksched_flight_dumps_total", reason="noop_round") == 1


def test_degrading_solver_rung_gauge_starts_at_minus_one():
    # before the first solve lands, ksched_solver_rung must read -1
    # ("none yet"), not 0 (the top production rung)
    from ksched_tpu.runtime.degrade import DegradingSolver

    with scoped_registry() as reg:
        DegradingSolver([("only", object())])
        assert reg.value("ksched_solver_rung") == -1


def test_flight_dump_creates_missing_dir(tmp_path):
    # --flight-dir on a fresh checkout: the dir does not exist yet, and
    # a failed dump must not kill the service loop it post-mortems
    fl = FlightRecorder(capacity=2, dump_dir=str(tmp_path / "flight"),
                        registry=Registry(), min_rounds_between_dumps=1)
    path = fl.note_round(_rec(0, deadline_miss=True))
    assert path is not None and json.loads(open(path).read())["reason"] == "deadline_miss"


def test_flight_scope_discriminates_same_round_dumps(tmp_path):
    """REGRESSION (multi-tenant satellite): auto-dump filenames were
    round-keyed only, so two tenants dumping in the same round
    clobbered each other. Scoped recorders must write distinct files,
    and even an unscoped name collision falls back to a suffix instead
    of overwriting."""
    reg = Registry()
    a = FlightRecorder(capacity=2, dump_dir=str(tmp_path), registry=reg,
                       min_rounds_between_dumps=1, scope="tenant_a")
    b = FlightRecorder(capacity=2, dump_dir=str(tmp_path), registry=reg,
                       min_rounds_between_dumps=1, scope="tenant_b")
    pa = a.note_round(_rec(0, noop_round=True))
    pb = b.note_round(_rec(0, noop_round=True))
    assert pa != pb and pa is not None and pb is not None
    assert "tenant_a" in pa and "tenant_b" in pb
    assert json.loads(open(pa).read())["scope"] == "tenant_a"
    # unscoped recorders at the same round index no longer clobber
    u1 = FlightRecorder(capacity=2, dump_dir=str(tmp_path), registry=reg,
                        min_rounds_between_dumps=1)
    u2 = FlightRecorder(capacity=2, dump_dir=str(tmp_path), registry=reg,
                        min_rounds_between_dumps=1)
    p1 = u1.note_round(_rec(0, noop_round=True))
    p2 = u2.note_round(_rec(1, noop_round=True))
    assert p1 != p2
    assert json.loads(open(p1).read())["rounds"][0]["record"]["round_index"] == 0
    assert json.loads(open(p2).read())["rounds"][0]["record"]["round_index"] == 1


def test_flight_scope_filters_stall_attribution(tmp_path):
    """Tenant-scoped dumps carry only their own (or untagged) soltel
    stall events; stall_scope tags events with the ambient tenant."""
    from ksched_tpu.obs import soltel

    soltel.reset_stalls()
    with scoped_registry():
        with soltel.stall_scope("tenant_a"):
            soltel.note_stall({"kind": "excess_plateau"})
        with soltel.stall_scope("tenant_b"):
            soltel.note_stall({"kind": "eps_plateau"})
        soltel.note_stall({"kind": "backend_error"})  # untagged
        fl = FlightRecorder(capacity=2, dump_dir=str(tmp_path),
                            registry=Registry(), scope="tenant_a",
                            min_rounds_between_dumps=1)
        path = fl.note_round(_rec(0, noop_round=True))
    stalls = json.loads(open(path).read())["solver_stalls"]
    kinds = {s["kind"] for s in stalls}
    assert kinds == {"excess_plateau", "backend_error"}
    assert {s.get("tenant") for s in stalls} == {"tenant_a", None}
    soltel.reset_stalls()


def test_flight_crash_hook_chains(tmp_path):
    import sys

    reg = Registry()
    fl = FlightRecorder(capacity=2, dump_dir=str(tmp_path), registry=reg)
    fl.note_round(_rec(0))
    seen = []
    prev_hook = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    try:
        fl.install_crash_hook()
        fl.install_crash_hook()  # idempotent
        try:
            raise ValueError("simulated crash")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        assert len(seen) == 1  # previous hook still ran
        assert len(fl.dumps) == 1 and "crash" in fl.dumps[0]
    finally:
        fl.uninstall_crash_hook()
        sys.excepthook = prev_hook


# ---------------------------------------------------------------------------
# devprof
# ---------------------------------------------------------------------------


def test_devprof_accounting():
    class P:
        num_arcs = 7
        num_nodes = 5
        cost = np.zeros(7, np.int32)
        cap = np.zeros(7, np.int32)

    class Stats:
        nodes_added = 2
        nodes_removed = 1
        arcs_added = 3
        arcs_changed = 4
        arcs_removed = 0

    reg = Registry()
    prof = DeviceProfiler(registry=reg)
    prof.note_export(P(), full=True)
    assert reg.value("ksched_h2d_bytes_total", kind="full_build") == 7 * 4 * 2
    prof.note_export(P(), full=False, stats=Stats())
    assert reg.value("ksched_h2d_bytes_total", kind="delta") == delta_nbytes(Stats())
    assert delta_nbytes(Stats()) == 7 * ARC_RECORD_BYTES + 3 * 9

    # journal form: counted from the applied changes themselves (arc
    # records carry src/dst), the exact scatter the round shipped
    class ArcChange:
        src, dst = 1, 2

    class NodeChange:
        pass

    before = reg.value("ksched_h2d_bytes_total", kind="delta")
    prof.note_export(P(), full=False, changes=[ArcChange(), ArcChange(), NodeChange()])
    assert journal_nbytes([ArcChange(), ArcChange(), NodeChange()]) == (
        2 * ARC_RECORD_BYTES + 9
    )
    assert (
        reg.value("ksched_h2d_bytes_total", kind="delta") - before
        == 2 * ARC_RECORD_BYTES + 9
    )

    class Backend:
        last_rung_name = "jax"
        last_iterations = 12

    class Result:
        iterations = 0

    prof.solve_starting()
    prof.note_solve(Backend(), P(), Result())
    assert reg.value("ksched_solves_total", backend="jax") == 1
    assert reg.value("ksched_solver_work", backend="jax") == 1  # one observation


# ---------------------------------------------------------------------------
# http_api stats hammer (the counters race the watch threads fixed)
# ---------------------------------------------------------------------------


def test_http_api_stats_hammer():
    from ksched_tpu.cluster.http_api import HTTPClusterAPI

    # poll_interval huge: the watch threads sleep on the stop event and
    # never touch the network, leaving the counters to the hammer
    api = HTTPClusterAPI("http://127.0.0.1:1", poll_interval_s=3600.0)
    try:
        keys = ("binding_retries", "binding_drops", "watch_retries")
        n_threads, n_inc = 8, 500
        start = threading.Barrier(n_threads)

        def hammer(k):
            start.wait()
            for _ in range(n_inc):
                api._count(k)

        threads = [
            threading.Thread(target=hammer, args=(keys[i % len(keys)],))
            for i in range(n_threads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        got = api.stats()
        per_key = {k: n_inc * sum(1 for i in range(n_threads) if keys[i % 3] == k)
                   for k in keys}
        assert got == per_key, f"lost updates: {got} != {per_key}"
    finally:
        api.close()


def test_http_api_private_registries_do_not_alias():
    from ksched_tpu.cluster.http_api import HTTPClusterAPI

    a = HTTPClusterAPI("http://127.0.0.1:1", poll_interval_s=3600.0)
    b = HTTPClusterAPI("http://127.0.0.1:1", poll_interval_s=3600.0)
    try:
        a._count("binding_retries", 3)
        assert a.stats() == {"binding_retries": 3}
        assert b.stats() == {}
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# RoundTracer <-> registry reconciliation + idle-sweep summary
# ---------------------------------------------------------------------------


def test_tracer_publishes_records_to_registry():
    reg = Registry()
    tracer = RoundTracer(registry=reg)
    for i in range(3):
        tracer._append(_rec(i, num_scheduled=2, faults_injected={"binding_drop": 1},
                            retries=2, degradations=1))
    tracer._append(_rec(3, solver_rung=-1))  # idle sweep
    tracer._append(_rec(4, solver_rung=-1, noop_round=True, deadline_miss=True))
    assert reg.value("ksched_rounds_total", kind="sched") == 3
    assert reg.value("ksched_rounds_total", kind="idle") == 1
    assert reg.value("ksched_rounds_total", kind="noop") == 1
    assert reg.value("ksched_scheduled_tasks_total") == 6
    assert reg.value("ksched_faults_attributed_total", kind="binding_drop") == 3
    assert reg.value("ksched_retries_total") == 6
    assert reg.value("ksched_round_degradations_total") == 3
    assert reg.value("ksched_deadline_misses_total") == 1
    # phase histogram only sees the 3 solved rounds
    assert reg.value("ksched_round_phase_ms", phase="total") == 3


def test_summary_excludes_idle_sweeps():
    tracer = RoundTracer(registry=Registry())
    for i in range(4):
        rec = _rec(i)
        rec.phases_ms = {"total": 10.0}
        tracer._append(rec)
    for i in range(4, 20):  # idle-heavy soak: 16 zero-timing sweeps
        rec = _rec(i, solver_rung=-1)
        rec.phases_ms = {"total": 0.0}
        tracer._append(rec)
    s = tracer.summary("total")
    assert s["rounds"] == 4 and s["idle_rounds"] == 16
    assert s["p50_ms"] == 10.0  # idle sweeps no longer drag p50 to zero
    empty = RoundTracer(registry=Registry())
    assert empty.summary() == {"rounds": 0, "idle_rounds": 0}


# ---------------------------------------------------------------------------
# end-to-end: instrumented service rounds
# ---------------------------------------------------------------------------


def _run_service_rounds(tmp_path, **svc_kw):
    from ksched_tpu.cli import SchedulerService
    from ksched_tpu.cluster import PodEvent, SyntheticClusterAPI

    api = SyntheticClusterAPI()
    svc = SchedulerService(api, backend_name="ref", **svc_kw)
    svc.init_topology(fake_machines=2)
    for i in range(4):
        api.submit_pod(PodEvent(pod_id=f"p{i}"))
    svc.run_round(api.poll_pod_batch(0.05))
    svc.run_round([], solve=False)
    api.close()
    return svc


def test_service_round_timing_is_span_durations(tmp_path):
    with scoped_registry():
        st = SpanTracer().install()
        try:
            svc = _run_service_rounds(tmp_path, span_tracer=st,
                                      tracer=RoundTracer())
        finally:
            st.uninstall()
        by_name = {}
        for ev in st.events():
            by_name.setdefault(ev["name"], []).append(ev)
        # RoundTiming is DERIVED from these spans: the round record's
        # phase values equal the span durations exactly
        rec = svc.tracer.records[0]
        for phase in ("stats", "graph_update", "deltas", "apply"):
            (ev,) = by_name[phase]
            assert rec.phases_ms[phase] == pytest.approx(ev["dur"] / 1e3)
        (round_ev,) = by_name["round"]
        assert rec.phases_ms["total"] == pytest.approx(round_ev["dur"] / 1e3)
        assert round_ev["args"]["parent"] == "service_round"
        # nested solve chain: solve -> ladder -> concrete backend
        solves = by_name["backend_solve"]
        assert {e["args"]["backend"] for e in solves} >= {"ReferenceSolver"}


def test_service_noop_round_trips_flight_dump(tmp_path):
    from ksched_tpu.runtime import ChaosPolicy, FaultInjector

    with scoped_registry() as reg:
        injector = FaultInjector(
            ChaosPolicy(seed=1, solver_total_outage_prob=1.0)
        )
        injector.begin_round(0)
        fl = FlightRecorder(capacity=8, dump_dir=str(tmp_path), registry=reg)
        with pytest.warns(RuntimeWarning):
            svc = _run_service_rounds(
                tmp_path, injector=injector, tracer=RoundTracer(),
                flight=fl,
            )
        assert svc.noop_rounds == 1
        assert len(fl.dumps) == 1
        doc = json.loads(open(fl.dumps[0]).read())
        assert doc["reason"] == "noop_round"
        assert doc["rounds"][0]["record"]["noop_round"] is True
        assert reg.value("ksched_rounds_total", kind="noop") == 1
        assert reg.value("ksched_ladder_exhausted_total") == 1
