"""Preemption on the device/bulk path: the tiered (continuation-priced)
transport and its keep-arcs semantics (graph_manager.go:855-888,
capacity rule :662-667), checked three ways:

- the tiered kernel against a parallel-arc SSP oracle (exactness);
- MIGRATE parity: an interference-cost shift must move the same tasks
  on the device path as on the host graph path (FlowScheduler with
  preemption=True and a matching cost model);
- PREEMPT parity: a cost spike above the escape cost must evict on
  both paths.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
from ksched_tpu.solver.layered import transport_fori_tiered


# ---------------------------------------------------------------------------
# tiered transport exactness vs a parallel-arc oracle
# ---------------------------------------------------------------------------


def _oracle_objective(wLo, wHi, R, supply, col_cap):
    """SSP reference solve of the parallel-arc expansion: per cell, a
    cheap arc (cap R, cost wLo) plus a base arc (the rest at wHi)."""
    from ksched_tpu.graph.device_export import FlowProblem
    from ksched_tpu.solver.cpu_ref import ReferenceSolver

    C, Mp1 = wLo.shape
    sink = C + Mp1
    src, dst, cap, cost = [], [], [], []
    U = np.minimum(supply[:, None], col_cap[None, :])
    Re = np.minimum(R, U)
    for c in range(C):
        for m in range(Mp1):
            if Re[c, m] > 0:
                src.append(c); dst.append(C + m)
                cap.append(Re[c, m]); cost.append(wLo[c, m])
            if U[c, m] - Re[c, m] > 0:
                src.append(c); dst.append(C + m)
                cap.append(U[c, m] - Re[c, m]); cost.append(wHi[c, m])
    for m in range(Mp1):
        src.append(C + m); dst.append(sink)
        cap.append(col_cap[m]); cost.append(0)
    excess = np.zeros(C + Mp1 + 1, np.int64)
    excess[:C] = supply
    excess[sink] = -supply.sum()
    p = FlowProblem(
        num_nodes=C + Mp1 + 1, excess=excess,
        node_type=np.zeros(C + Mp1 + 1, np.int8),
        src=np.array(src, np.int32), dst=np.array(dst, np.int32),
        cap=np.array(cap, np.int32), cost=np.array(cost, np.int32),
        flow_offset=np.zeros(len(src), np.int32), num_arcs=len(src),
    )
    return ReferenceSolver().solve(p).objective


def test_tiered_transport_matches_parallel_arc_oracle():
    rng = np.random.default_rng(3)
    solve = jax.jit(transport_fori_tiered, static_argnums=(5, 6, 7))
    for trial in range(12):
        C = int(rng.integers(2, 5))
        Mp1 = int(rng.integers(3, 9)) + 1
        n_scale = 64  # > node count: eps=1 termination is exact
        w = rng.integers(-8, 9, (C, Mp1)).astype(np.int32) * n_scale
        w[:, -1] = 0  # unsched column
        d = rng.integers(0, 5, (C, Mp1)).astype(np.int32) * n_scale
        d[:, -1] = 0
        supply = rng.integers(0, 12, C).astype(np.int32)
        col_cap = rng.integers(0, 6, Mp1).astype(np.int32)
        col_cap[-1] = supply.sum()
        R = rng.integers(0, 4, (C, Mp1)).astype(np.int32)
        R[:, -1] = 0
        y, _pm, steps, conv = solve(
            jnp.asarray(w - d), jnp.asarray(w), jnp.asarray(R),
            jnp.asarray(supply), jnp.asarray(col_cap),
            50_000, 8, n_scale // 16,
        )
        assert bool(conv), f"trial {trial} did not converge"
        y = np.asarray(y)
        U = np.minimum(supply[:, None], col_cap[None, :])
        Re = np.minimum(R, U)
        assert (y >= 0).all() and (y <= U).all()
        assert (y.sum(axis=1) == supply).all()
        assert (y.sum(axis=0) <= col_cap).all()
        yA = np.minimum(y, Re)
        obj = int(((w - d) * yA).sum() + (w * (y - yA)).sum())
        assert obj == _oracle_objective(
            w - d, w, R, supply.astype(np.int64), col_cap.astype(np.int64)
        ), f"trial {trial}: objective mismatch"


# ---------------------------------------------------------------------------
# graph-path parity scenarios
# ---------------------------------------------------------------------------

UNSCHED = 30
DISCOUNT = 1


def _build_graph_cluster(num_machines, slots, interference, base_scale):
    """FlowScheduler with preemption=True and a cost model matching the
    device twin: cost[c, m] = interference * other_class_running(m)
    + (1 + c) * base_scale * machine_index(m); continuation = current
    machine's cost - DISCOUNT; escape/preemption = UNSCHED."""
    from ksched_tpu.costmodels.census import CLASS_ECS
    from ksched_tpu.costmodels.coco import CocoCostModel
    from ksched_tpu.drivers import build_cluster
    from ksched_tpu.utils import resource_id_from_string

    class ShiftModel(CocoCostModel):
        machine_index = {}  # rid -> index, filled after build

        def _machine_cost(self, task_class, resource_id):
            census = self.census.machine_census(resource_id)
            other = int(census.sum()) - int(census[task_class])
            return (
                interference * other
                + (1 + task_class) * base_scale * self.machine_index[resource_id]
            )

        def task_to_unscheduled_agg_cost(self, task_id):
            return UNSCHED

        def task_preemption_cost(self, task_id):
            return UNSCHED

        def task_continuation_cost(self, task_id):
            td = self.task_map.find(task_id)
            rid = resource_id_from_string(td.scheduled_to_resource)
            while rid not in self.machine_index:
                rs = self.resource_map.find(rid)
                rid = resource_id_from_string(rs.topology_node.parent_id)
            c = self.census.task_class(task_id)
            return self._machine_cost(c, rid) - DISCOUNT

        def equiv_class_to_resource_node(self, ec, resource_id):
            from ksched_tpu.costmodels.census import ec_class

            c = ec_class(ec)
            if c is None:
                return 0, 0
            rs = self.resource_map.find(resource_id)
            # preemption-on capacity: TOTAL slots (rule :662-667 flips)
            return self._machine_cost(c, resource_id), rs.descriptor.num_slots_below

    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=num_machines, num_cores=1, pus_per_core=1,
        max_tasks_per_pu=slots, cost_model_factory=ShiftModel,
        preemption=True,
    )
    for i, child in enumerate(root.children):
        rid = resource_id_from_string(child.resource_desc.uuid)
        ShiftModel.machine_index[rid] = i
    return sched, rmap, jmap, tmap, root, ShiftModel.machine_index


def _add_tasks_with_classes(sched, jmap, tmap, jid, classes):
    from ksched_tpu.data import TaskType
    from ksched_tpu.drivers.synthetic import add_task_to_job

    tds = []
    for c in classes:
        td = add_task_to_job(jid, jmap, tmap)
        td.task_type = TaskType(c)
        tds.append(td)
    jd = jmap.find(jid)
    if jid not in sched.jobs_to_schedule:
        sched.add_job(jd)
    return tds


def _delta_counts(deltas):
    from ksched_tpu.data import DeltaType

    out = {"PLACE": 0, "MIGRATE": 0, "PREEMPT": 0}
    for d in deltas:
        if d.type == DeltaType.PLACE:
            out["PLACE"] += 1
        elif d.type == DeltaType.MIGRATE:
            out["MIGRATE"] += 1
        elif d.type == DeltaType.PREEMPT:
            out["PREEMPT"] += 1
    return out


def _graph_census(sched, tmap, machine_index, rmap, num_machines):
    """per-(machine, class) running counts from the bindings."""
    from ksched_tpu.utils import resource_id_from_string

    census = np.zeros((num_machines, 4), np.int64)
    for tid, rid in sched.task_bindings.items():
        while rid not in machine_index:
            rs = rmap.find(rid)
            rid = resource_id_from_string(rs.topology_node.parent_id)
        census[machine_index[rid], int(tmap.find(tid).task_type)] += 1
    return census


def _device_cost_fn(interference, base_scale, M):
    base = jnp.arange(M, dtype=jnp.int32)

    def cost_fn(census):  # census [M, C]
        other = census.sum(axis=1, keepdims=True) - census  # [M, C]
        C = census.shape[1]
        scale = (1 + jnp.arange(C, dtype=jnp.int32))[:, None]  # [C, 1]
        return (interference * other.T + scale * base_scale * base[None, :]).astype(
            jnp.int32
        )

    return cost_fn


def test_device_preemption_migration_parity_with_graph_path():
    """Interference shift: two co-located tasks of different classes;
    a third arrival makes class 0 cheaper elsewhere. Unique optimum:
    the class-0 resident MIGRATES, the arrival PLACES next to it, the
    class-1 resident stays. Both paths must agree."""
    rng_classes = [0, 1]
    sched, rmap, jmap, tmap, root, machine_index = _build_graph_cluster(
        num_machines=2, slots=2, interference=10, base_scale=1
    )
    from ksched_tpu.utils import rand_uint64

    jid = rand_uint64()
    _add_tasks_with_classes(sched, jmap, tmap, jid, rng_classes)
    n, deltas = sched.schedule_all_jobs()
    assert n == 2
    census1 = _graph_census(sched, tmap, machine_index, rmap, 2)
    assert census1[0, 0] == 1 and census1[0, 1] == 1  # both on machine 0

    _add_tasks_with_classes(sched, jmap, tmap, jid, [0])
    n2, deltas2 = sched.schedule_all_jobs()
    graph_counts = _delta_counts(deltas2)
    census2 = _graph_census(sched, tmap, machine_index, rmap, 2)
    assert graph_counts == {"PLACE": 1, "MIGRATE": 1, "PREEMPT": 0}
    assert census2[1, 0] == 2 and census2[0, 1] == 1

    # device twin, same scenario
    dev = DeviceBulkCluster(
        num_machines=2, pus_per_machine=1, slots_per_pu=2, num_jobs=1,
        num_task_classes=2, task_capacity=16,
        class_cost_fn=_device_cost_fn(10, 1, 2),
        preemption=True, continuation_discount=DISCOUNT,
        unsched_cost=UNSCHED, ec_cost=0,
    )
    dev.add_tasks(2, classes=np.array(rng_classes, np.int32))
    s1 = dev.fetch_stats(dev.round())
    assert bool(s1["converged"]) and int(s1["placed"]) == 2
    dev.add_tasks(1, classes=np.array([0], np.int32))
    s2 = dev.fetch_stats(dev.round())
    assert bool(s2["converged"])
    dev_counts = {
        "PLACE": int(s2["placed"]),
        "MIGRATE": int(s2["migrated"]),
        "PREEMPT": int(s2["preempted"]),
    }
    assert dev_counts == graph_counts
    st = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    on = st["live"] & (st["pu"] >= 0)
    dev_census = np.zeros((2, 2), np.int64)
    np.add.at(dev_census, (st["pu"][on], st["cls"][on]), 1)
    assert (dev_census == census2[:, :2]).all()


def test_device_preemption_preempt_parity_with_graph_path():
    """Cost spike above the escape price: the resident is PREEMPTED
    (continuation 34 > escape 30) and the arrival stays unscheduled on
    both paths."""
    sched, rmap, jmap, tmap, root, machine_index = _build_graph_cluster(
        num_machines=1, slots=1, interference=0, base_scale=0
    )

    # cost = 35 * running count on the machine (same-class interference)
    class_ = 0

    def patch(model_cls):
        def _machine_cost(self, task_class, resource_id):
            census = self.census.machine_census(resource_id)
            return 35 * int(census.sum())

        model_cls._machine_cost = _machine_cost

    patch(type(sched.cost_model))

    from ksched_tpu.utils import rand_uint64

    jid = rand_uint64()
    _add_tasks_with_classes(sched, jmap, tmap, jid, [class_])
    n, _ = sched.schedule_all_jobs()
    assert n == 1
    _add_tasks_with_classes(sched, jmap, tmap, jid, [class_])
    _n2, deltas2 = sched.schedule_all_jobs()
    graph_counts = _delta_counts(deltas2)
    assert graph_counts == {"PLACE": 0, "MIGRATE": 0, "PREEMPT": 1}
    assert not sched.task_bindings  # everyone off the machine

    def cost_fn(census):
        return (35 * census.sum(axis=1, keepdims=True).T).astype(jnp.int32)

    dev = DeviceBulkCluster(
        num_machines=1, pus_per_machine=1, slots_per_pu=1, num_jobs=1,
        num_task_classes=1, task_capacity=8, class_cost_fn=cost_fn,
        preemption=True, continuation_discount=DISCOUNT,
        unsched_cost=UNSCHED, ec_cost=0,
    )
    dev.add_tasks(1)
    s1 = dev.fetch_stats(dev.round())
    assert int(s1["placed"]) == 1
    dev.add_tasks(1)
    s2 = dev.fetch_stats(dev.round())
    assert bool(s2["converged"])
    assert {
        "PLACE": int(s2["placed"]),
        "MIGRATE": int(s2["migrated"]),
        "PREEMPT": int(s2["preempted"]),
    } == graph_counts
    assert dev.num_placed_tasks == 0
    assert int(s2["unscheduled"]) == 2


def test_device_preemption_accepts_mover_decode_window():
    """decode_width in preemption mode bounds the MOVER decode (round-3
    feature; behavioral coverage in test_bounded_decode.py)."""
    dev = DeviceBulkCluster(
        num_machines=2, pus_per_machine=1, slots_per_pu=1, num_jobs=1,
        task_capacity=16, preemption=True, decode_width=4,
    )
    assert dev.decode_width == 4 and dev.preemption


# ---------------------------------------------------------------------------
# stability-aware (incremental) preemption — preempt_every / preempt_drift
# ---------------------------------------------------------------------------


def _hybrid_cluster(every, drift, seed=7, M=40, T=400):
    from ksched_tpu.costmodels import coco
    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn

    rng = np.random.default_rng(seed)
    penalties = rng.integers(0, 40, (M, 4)).astype(np.int64)
    dev = DeviceBulkCluster(
        num_machines=M, pus_per_machine=4, slots_per_pu=4, num_jobs=4,
        num_task_classes=4, task_capacity=1024,
        class_cost_fn=coco_device_cost_fn(penalties),
        unsched_cost=coco.UNSCHEDULED_COST, ec_cost=0,
        supersteps=1 << 16, preemption=True, continuation_discount=8,
        preempt_every=every, preempt_drift=drift, decode_width=256,
        track_realized_cost=True,
    )
    dev.add_tasks(T, rng.integers(0, 4, T).astype(np.int32),
                  rng.integers(0, 4, T).astype(np.int32))
    jax.block_until_ready(dev.round())
    return dev


def test_hybrid_preemption_schedule_and_drift_trigger():
    """preempt_every=K fires the full tiered re-solve on cadence; the
    census-drift trigger adds full rounds when placements churn past
    the threshold; incremental rounds report zero migrations and pin
    residents (the reference's delta-proportional round property,
    placement/solver.go:60-90)."""
    dev = _hybrid_cluster(every=8, drift=0)
    s = dev.fetch_stats(dev.run_steady_rounds(32, 0.05, 20, seed=3))
    assert s["converged"].all()
    full = s["full_round"].astype(bool)
    # cadence: the fill round() was full and reset the counter, so
    # the scan's full rounds land every 8th from index 7
    assert full.sum() == 4
    assert (np.nonzero(full)[0] == np.array([7, 15, 23, 31])).all()
    # incremental rounds never migrate or preempt
    incr = ~full
    assert (s["migrated"][incr] == 0).all()
    assert (s["preempted"][incr] == 0).all()

    # occupancy invariant after the mixed scan
    st = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    on = st["live"] & (st["pu"] >= 0)
    recount = np.bincount(st["pu"][on], minlength=dev.num_pus)
    assert (recount == st["pu_running"]).all()

    # the drift trigger alone (cadence effectively off) also fires
    dev2 = _hybrid_cluster(every=1 << 20, drift=60)
    s2 = dev2.fetch_stats(dev2.run_steady_rounds(32, 0.05, 20, seed=3))
    full2 = s2["full_round"].astype(bool)
    assert s2["converged"].all()
    assert 0 < full2[1:].sum() < 31, "drift trigger should fire sometimes"
    # every fired round saw drift >= threshold (beyond the forced first)
    fired = np.nonzero(full2)[0]
    fired = fired[fired > 0]
    assert (s2["census_drift"][fired] >= 60).all()


def test_hybrid_preemption_objective_drift_bounded():
    """The stability-aware scheme's realized cluster cost must track
    the full-re-solve-every-round regime within a small bound — the
    parity contract for VERDICT r3 #1 (incremental preemption must not
    silently degrade placement quality)."""
    # baseline: full solve EVERY round, expressed through the hybrid
    # wrapper (preempt_every=1 with a token drift threshold) so both
    # runs report the same realized_cost metric
    base = _hybrid_cluster(every=1, drift=1 << 30)
    sb = base.fetch_stats(base.run_steady_rounds(48, 0.05, 20, seed=5))
    hyb = _hybrid_cluster(every=8, drift=0)
    sh = hyb.fetch_stats(hyb.run_steady_rounds(48, 0.05, 20, seed=5))
    assert sb["converged"].all() and sh["converged"].all()
    rb = sb["realized_cost"].astype(np.float64)
    rh = sh["realized_cost"].astype(np.float64)
    # same churn stream (same seed): compare round for round
    rel = (rh - rb) / np.maximum(rb, 1.0)
    # bound DEGRADATION only: measured, the hybrid runs consistently
    # CHEAPER on realized interference cost (pinning residents avoids
    # the census-feedback thrash of re-migrating every round), so the
    # negative side is a win, not drift
    assert rel.mean() < 0.05, f"mean drift {rel.mean():.3f}"
    assert rel.max() < 0.25, f"max degradation {rel.max():.3f}"


def test_hybrid_preemption_checkpoint_roundtrip(tmp_path):
    """Hybrid-mode checkpoints carry preempt_every/preempt_drift AND
    the stability carry (drift-reference census + rounds-since-full),
    so a restored cluster resumes the EXACT cadence in lockstep with
    the original — identical full-round schedules, bit-identical
    states."""
    from ksched_tpu.costmodels import coco
    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn
    from ksched_tpu.runtime.checkpoint import (
        load_device_checkpoint,
        save_device_checkpoint,
    )

    dev = _hybrid_cluster(every=4, drift=100)
    dev.fetch_stats(dev.run_steady_rounds(8, 0.05, 10, seed=2))
    path = str(tmp_path / "hyb.npz")
    save_device_checkpoint(dev, path)

    rng = np.random.default_rng(7)
    penalties = rng.integers(0, 40, (40, 4)).astype(np.int64)
    back = load_device_checkpoint(
        path, class_cost_fn=coco_device_cost_fn(penalties)
    )
    assert back.preempt_every == 4 and back.preempt_drift == 100
    assert back.hybrid_preempt
    for k, v in back.fetch_state().items():
        assert np.array_equal(np.asarray(v), np.asarray(dev.fetch_state()[k])), k
    # the hybrid carry round-trips too: original and restored proceed
    # in LOCKSTEP — identical full-round schedules and bit-identical
    # states (exact cadence resume, not a conservative re-fire)
    assert np.array_equal(np.asarray(back._hyb_census),
                          np.asarray(dev._hyb_census))
    assert int(back._hyb_k) == int(dev._hyb_k)
    sa = dev.fetch_stats(dev.run_steady_rounds(8, 0.05, 10, seed=3))
    sb = back.fetch_stats(back.run_steady_rounds(8, 0.05, 10, seed=3))
    assert sa["converged"].all() and sb["converged"].all()
    assert np.array_equal(sa["full_round"], sb["full_round"])
    for k, v in back.fetch_state().items():
        assert np.array_equal(np.asarray(v), np.asarray(dev.fetch_state()[k])), k


def test_hybrid_preemption_replay_scan():
    """The stability-aware branches must also serve the REPLAY scan
    (run_replay_rounds): staged completions/admissions/toggles chain
    through the hybrid carry, full rounds fire on cadence, and
    occupancy invariants hold at the end."""
    dev = _hybrid_cluster(every=4, drift=0, T=200)
    K, Amax, Dmax, Emax = 12, 8, 4, 2
    rng = np.random.default_rng(3)
    sch = {
        "adm_job": rng.integers(0, 4, (K, Amax)).astype(np.int32),
        "adm_cls": rng.integers(0, 4, (K, Amax)).astype(np.int32),
        "adm_grp": np.zeros((K, Amax), np.int32),
        "adm_n": np.full(K, Amax, np.int32),
        "done_rows": np.full((K, Dmax), dev.Tcap, np.int32),
        "done_n": np.zeros(K, np.int32),
        "tog_idx": np.zeros((K, Emax), np.int32),
        "tog_on": np.ones((K, Emax), bool),
        "tog_n": np.zeros(K, np.int32),
        "rounds": K,
    }
    # retire a fixed early row block in later windows (they were
    # admitted by the fill in _hybrid_cluster)
    for i in range(4, K):
        sch["done_rows"][i, :2] = [(i - 4) * 2, (i - 4) * 2 + 1]
        sch["done_n"][i] = 2
    s = dev.fetch_stats(dev.run_replay_rounds(sch, seed=5))
    assert s["converged"].all()
    full = s["full_round"].astype(bool)
    assert full.sum() == K // 4 and (np.nonzero(full)[0] % 4 == 3).all()
    st = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    on = st["live"] & (st["pu"] >= 0)
    recount = np.bincount(st["pu"][on], minlength=dev.num_pus)
    assert (recount == st["pu_running"]).all()


# ---------------------------------------------------------------------------
# three-tier stability: scoped re-solves + rare global rounds
# ---------------------------------------------------------------------------


def _tri_cluster(every, global_every, seed=7, M=40, T=400, drift=0,
                 incr_budget=None, scoped_width=None):
    from ksched_tpu.costmodels import coco
    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn

    rng = np.random.default_rng(seed)
    penalties = rng.integers(0, 40, (M, 4)).astype(np.int64)
    dev = DeviceBulkCluster(
        num_machines=M, pus_per_machine=4, slots_per_pu=4, num_jobs=4,
        num_task_classes=4, task_capacity=1024,
        class_cost_fn=coco_device_cost_fn(penalties),
        unsched_cost=coco.UNSCHEDULED_COST, ec_cost=0,
        supersteps=1 << 16, preemption=True, continuation_discount=8,
        preempt_every=every, preempt_drift=drift,
        preempt_global_every=global_every,
        preempt_incr_budget=incr_budget,
        preempt_scoped_width=scoped_width,
        decode_width=256, track_realized_cost=True,
    )
    dev.add_tasks(T, rng.integers(0, 4, T).astype(np.int32),
                  rng.integers(0, 4, T).astype(np.int32))
    jax.block_until_ready(dev.round())
    return dev


def test_scoped_preemption_pins_out_of_scope_residents():
    """A scoped re-solve may only move residents of machines whose
    census drifted since the last re-solve; everything else is pinned
    in place (VERDICT r4 #2 — re-price only the drifted columns). The
    replay stages completions on ONE known machine, so that machine is
    the entire scope of the cadence-fired scoped round."""
    dev = _tri_cluster(every=2, global_every=1000, T=600)
    st0 = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    on = st0["live"] & (st0["pu"] >= 0)
    pu0 = st0["pu"]
    m_of = np.clip(pu0, 0, dev.num_pus - 1) // dev.P
    # the busiest machine donates 3 completions
    counts = np.bincount(m_of[on], minlength=dev.M)
    m_star = int(np.argmax(counts))
    victims = np.nonzero(on & (m_of == m_star))[0][:3].astype(np.int32)
    assert len(victims) == 3

    K, Dmax = 2, 4
    sch = {
        "adm_job": np.zeros((K, 1), np.int32),
        "adm_cls": np.zeros((K, 1), np.int32),
        "adm_grp": np.zeros((K, 1), np.int32),
        "adm_n": np.zeros(K, np.int32),
        "done_rows": np.full((K, Dmax), dev.Tcap, np.int32),
        "done_n": np.zeros(K, np.int32),
        "tog_idx": np.zeros((K, 1), np.int32),
        "tog_on": np.ones((K, 1), bool),
        "tog_n": np.zeros(K, np.int32),
        "rounds": K,
    }
    sch["done_rows"][0, :3] = victims
    sch["done_n"][0] = 3
    s = dev.fetch_stats(dev.run_replay_rounds(sch, seed=5))
    assert s["converged"].all()
    # round 0 incremental (k=1 of 2), round 1 the cadence-fired SCOPED
    # re-solve; the global cadence (1000) never fires in this scan
    assert s["full_round"].tolist() == [False, True]
    assert s["global_round"].tolist() == [False, False]

    st1 = {k: np.asarray(v) for k, v in dev.fetch_state().items()}
    moved = (
        st0["live"] & st1["live"] & (pu0 >= 0) & (st1["pu"] != pu0)
    )
    # every moved resident came from the drifted machine
    assert moved.sum() == 0 or (m_of[moved] == m_star).all(), (
        np.unique(m_of[moved])
    )
    # occupancy invariant
    on1 = st1["live"] & (st1["pu"] >= 0)
    recount = np.bincount(st1["pu"][on1], minlength=dev.num_pus)
    assert (recount == st1["pu_running"]).all()


def test_three_tier_global_cadence_and_quality():
    """Global rounds fire on their own (rarer) cadence inside the
    scoped regime, and the three-tier scheme's realized cluster cost
    tracks the full-re-solve-every-round regime within the same bound
    the two-tier hybrid honors."""
    tri = _tri_cluster(every=4, global_every=16)
    s = tri.fetch_stats(tri.run_steady_rounds(32, 0.05, 20, seed=5))
    assert s["converged"].all()
    full = s["full_round"].astype(bool)
    glob = s["global_round"].astype(bool)
    assert (np.nonzero(full)[0] == np.array([3, 7, 11, 15, 19, 23, 27, 31])).all()
    assert (np.nonzero(glob)[0] == np.array([15, 31])).all()
    assert (full | ~glob).all()  # global rounds are full rounds

    base = _hybrid_cluster(every=1, drift=1 << 30)
    sb = base.fetch_stats(base.run_steady_rounds(48, 0.05, 20, seed=5))
    tri2 = _tri_cluster(every=8, global_every=32)
    st = tri2.fetch_stats(tri2.run_steady_rounds(48, 0.05, 20, seed=5))
    rb = sb["realized_cost"].astype(np.float64)
    rt = st["realized_cost"].astype(np.float64)
    rel = (rt - rb) / np.maximum(rb, 1.0)
    assert rel.mean() < 0.05, f"mean drift {rel.mean():.3f}"
    assert rel.max() < 0.25, f"max degradation {rel.max():.3f}"


def test_three_tier_checkpoint_lockstep(tmp_path):
    """The global-cadence counter rides the checkpoint carry: original
    and restored clusters fire identical scoped AND global schedules.
    The cluster sets preempt_incr_budget AND the degenerate
    preempt_scoped_width=0, so the round-trip covers both fields
    (ADVICE r5 #3): a falsy-coerced width or a dropped budget would
    break the lockstep resume asserted below."""
    from ksched_tpu.costmodels import coco
    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn
    from ksched_tpu.runtime.checkpoint import (
        load_device_checkpoint,
        save_device_checkpoint,
    )

    dev = _tri_cluster(every=2, global_every=8, incr_budget=1024,
                       scoped_width=0)
    dev.fetch_stats(dev.run_steady_rounds(5, 0.05, 10, seed=2))
    path = str(tmp_path / "tri.npz")
    save_device_checkpoint(dev, path)
    rng = np.random.default_rng(7)
    penalties = rng.integers(0, 40, (40, 4)).astype(np.int64)
    back = load_device_checkpoint(
        path, class_cost_fn=coco_device_cost_fn(penalties)
    )
    assert back.preempt_global_every == 8
    assert back.preempt_incr_budget == 1024
    assert back.preempt_scoped_width == 0
    assert int(back._hyb_kg) == int(dev._hyb_kg)
    sa = dev.fetch_stats(dev.run_steady_rounds(10, 0.05, 10, seed=3))
    sb = back.fetch_stats(back.run_steady_rounds(10, 0.05, 10, seed=3))
    assert np.array_equal(sa["full_round"], sb["full_round"])
    assert np.array_equal(sa["global_round"], sb["global_round"])
    for k, v in back.fetch_state().items():
        assert np.array_equal(np.asarray(v), np.asarray(dev.fetch_state()[k])), k


def test_checkpoint_scoped_width_zero_roundtrip(tmp_path):
    """A saved preempt_scoped_width of 0 (legal, degenerate: every
    scoped-round mover parks) must restore as 0, not be falsy-coerced
    to None (= Tcap-wide decode) — the restored cluster would grant
    movers the original parked, breaking lockstep resume."""
    from ksched_tpu.costmodels import coco
    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn
    from ksched_tpu.runtime.checkpoint import (
        load_device_checkpoint,
        save_device_checkpoint,
    )

    rng = np.random.default_rng(3)
    penalties = rng.integers(0, 40, (16, 4)).astype(np.int64)
    dev = DeviceBulkCluster(
        num_machines=16, pus_per_machine=2, slots_per_pu=2, num_jobs=2,
        num_task_classes=4, task_capacity=256,
        class_cost_fn=coco_device_cost_fn(penalties),
        unsched_cost=coco.UNSCHEDULED_COST, ec_cost=0,
        supersteps=1 << 14, preemption=True, continuation_discount=8,
        preempt_every=2, preempt_global_every=8,
        preempt_scoped_width=0, decode_width=64,
    )
    dev.add_tasks(60, rng.integers(0, 2, 60).astype(np.int32),
                  rng.integers(0, 4, 60).astype(np.int32))
    jax.block_until_ready(dev.round())
    path = str(tmp_path / "w0.npz")
    save_device_checkpoint(dev, path)
    back = load_device_checkpoint(
        path, class_cost_fn=coco_device_cost_fn(penalties)
    )
    assert back.preempt_scoped_width == 0
    # and a plain None width still restores as None
    dev2 = DeviceBulkCluster(
        num_machines=16, pus_per_machine=2, slots_per_pu=2, num_jobs=2,
        num_task_classes=4, task_capacity=256,
        class_cost_fn=coco_device_cost_fn(penalties),
        unsched_cost=coco.UNSCHEDULED_COST, ec_cost=0,
        supersteps=1 << 14, preemption=True, continuation_discount=8,
        preempt_every=2, preempt_global_every=8, decode_width=64,
    )
    dev2.add_tasks(60, rng.integers(0, 2, 60).astype(np.int32),
                   rng.integers(0, 4, 60).astype(np.int32))
    jax.block_until_ready(dev2.round())
    path2 = str(tmp_path / "wn.npz")
    save_device_checkpoint(dev2, path2)
    back2 = load_device_checkpoint(
        path2, class_cost_fn=coco_device_cost_fn(penalties)
    )
    assert back2.preempt_scoped_width is None


def test_incr_budget_escalates_to_scoped_parity():
    """A budget-exhausted incremental attempt is discarded and the
    round re-runs as a scoped re-solve: with a 1-superstep budget the
    escalated round's END STATE must be bit-identical to a twin whose
    drift trigger forces the scoped tier directly on the same pre-round
    state (the attempt leaves no trace but its superstep count)."""
    from ksched_tpu.costmodels import coco
    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn

    def build(incr_budget, drift):
        rng = np.random.default_rng(7)
        penalties = rng.integers(0, 40, (40, 4)).astype(np.int64)
        dev = DeviceBulkCluster(
            num_machines=40, pus_per_machine=4, slots_per_pu=4, num_jobs=4,
            num_task_classes=4, task_capacity=1024,
            class_cost_fn=coco_device_cost_fn(penalties),
            unsched_cost=coco.UNSCHEDULED_COST, ec_cost=0,
            supersteps=1 << 16, preemption=True, continuation_discount=8,
            preempt_every=1000, preempt_drift=drift,
            preempt_global_every=1000,
            decode_width=256, track_realized_cost=True,
            preempt_incr_budget=incr_budget,
        )
        rng2 = np.random.default_rng(7)
        dev.add_tasks(600, rng2.integers(0, 4, 600).astype(np.int32),
                      rng2.integers(0, 4, 600).astype(np.int32))
        jax.block_until_ready(dev.round())
        return dev

    a = build(incr_budget=1, drift=0)
    b = build(incr_budget=None, drift=1)  # any drift fires -> scoped
    sa = a.fetch_stats(a.run_steady_rounds(6, 0.05, 12, seed=5))
    sb = b.fetch_stats(b.run_steady_rounds(6, 0.05, 12, seed=5))
    esc = np.asarray(sa["escalated_round"])
    fb = np.asarray(sb["full_round"])
    # the contended 600-task/40-machine cluster has churn backlog every
    # round, so every A round's 1-superstep attempt fails (escalates)
    # and every B round sees census drift >= 1 (fires scoped) — assert
    # the preconditions so the parity check below can never silently
    # skip (review finding r5)
    assert esc.all(), f"expected every round to escalate, got {esc}"
    assert fb.all(), f"expected every twin round to fire scoped, got {fb}"
    for k, v in a.fetch_state().items():
        assert np.array_equal(
            np.asarray(v), np.asarray(b.fetch_state()[k])
        ), k
    # escalated rounds are fired rounds: cadence reset + census re-base
    assert np.asarray(sa["full_round"])[esc].all()
    # and the round still converged (via the scoped solve)
    assert np.asarray(sa["converged"]).all()


def test_incr_budget_none_is_bit_identical_to_r4_rounds():
    """preempt_incr_budget=None must leave the three-tier scheme's
    rounds bit-identical to the pre-knob behavior (same seeds)."""
    a = _tri_cluster(every=4, global_every=16)
    sa = a.fetch_stats(a.run_steady_rounds(8, 0.05, 10, seed=3))
    assert not np.asarray(sa.get("escalated_round", np.zeros(1))).any()
    b = _tri_cluster(every=4, global_every=16)
    sb = b.fetch_stats(b.run_steady_rounds(8, 0.05, 10, seed=3))
    for k in ("placed", "supersteps", "full_round", "global_round"):
        assert np.array_equal(np.asarray(sa[k]), np.asarray(sb[k])), k
