"""Solver-interior telemetry (obs/soltel.py + in-kernel counters).

The contract under test, per backend:

1. **Bit-identical flows on/off** — the telemetry counters read state
   each superstep already computes; they must never feed back. Checked
   for every compiled backend (jax, ell, mega, layered, sharded) at 3
   shape buckets, plus step-count equality.
2. **Explicit truncation** — a solve longer than the ring keeps the
   FINAL supersteps, reports `truncated` + `start_step`, and the kept
   rows match a full-capacity recording row for row.
3. **Stall detection** — the structured rules (excess plateau, eps
   plateau, budget exhaustion, cap proximity) fire on telemetry shaped
   like each pathology, and a genuine non-convergence raises
   SolverStallError carrying reason + telemetry.
4. **Flight integration** — a ladder failure deposits a structured
   stall event (with telemetry tail) that FlightRecorder.dump embeds.
5. **Publication** — solve_traced feeds the registry histograms and
   synthesizes per-superstep child spans under backend_solve.
"""

import numpy as np
import jax
import pytest
from jax.sharding import Mesh

from ksched_tpu.obs import soltel
from ksched_tpu.obs.metrics import Registry, scoped_registry
from ksched_tpu.obs.soltel import (
    SOLTEL_COLS,
    SOLTEL_WIDTH,
    SolverStallError,
    SolveTelemetry,
    decode,
    detect_stall,
)
from ksched_tpu.solver.ell_solver import EllSolver
from ksched_tpu.solver.jax_solver import JaxSolver
from ksched_tpu.solver.layered import (
    LayeredProblem,
    LayeredTransportSolver,
)
from ksched_tpu.solver.mega_solver import MegaSolver
from ksched_tpu.parallel.sharded_solver import ShardedJaxSolver

from test_jax_solver import random_scheduling_problem

#: 3 shape buckets (tasks, machines) for the bit-identity sweep —
#: distinct pow2 node/arc buckets, kept SMALL: every (backend, bucket,
#: cap) triple is a fresh compile and tier-1 has a hard wall
SHAPE_BUCKETS = [(8, 3), (14, 4), (22, 5)]

#: the one telemetry capacity the suite compiles (beyond 0/off) —
#: reused across tests so executables are shared via the jit cache
CAP = 64


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= 8
    return Mesh(np.array(devs[:8]), ("x",))


def _problem(tasks, machines, seed):
    rng = np.random.default_rng(seed)
    return random_scheduling_problem(
        rng, num_tasks=tasks, num_machines=machines, slots_per_machine=2
    )


def _general_backends(mesh):
    return {
        "jax": lambda tel: JaxSolver(telemetry=tel),
        "ell": lambda tel: EllSolver(telemetry=tel),
        "mega": lambda tel: MegaSolver(interpret=True, telemetry=tel),
        "sharded": lambda tel: ShardedJaxSolver(mesh, telemetry=tel),
    }


# ---------------------------------------------------------------------------
# 1. bit-identical flows, telemetry on vs off
# ---------------------------------------------------------------------------


#: sharded bit-identity beyond the first bucket is slow-marked: each
#: (bucket, on/off) pair is a fresh shard_map compile (~8 s), and the
#: budgeted tier-1 wall is compile-bound (same reasoning that
#: slow-marks test_sharded_transport); `pytest tests/` runs all three
_SWEEP = [("jax", b) for b in SHAPE_BUCKETS] + \
    [("ell", b) for b in SHAPE_BUCKETS] + \
    [("mega", b) for b in SHAPE_BUCKETS] + \
    [("sharded", SHAPE_BUCKETS[0])] + [
        pytest.param("sharded", b, marks=pytest.mark.slow)
        for b in SHAPE_BUCKETS[1:]
    ]


@pytest.mark.parametrize("backend,bucket", _SWEEP, ids=str)
def test_flows_bit_identical_on_off(backend, bucket, mesh):
    make = _general_backends(mesh)[backend]
    p = _problem(*bucket, seed=7)
    r_off = make(0).solve(p)
    s_on = make(CAP)
    r_on = s_on.solve(p)
    assert np.array_equal(r_on.flow, r_off.flow), backend
    assert r_on.objective == r_off.objective
    assert r_on.iterations == r_off.iterations
    tel = s_on.last_telemetry
    assert isinstance(tel, SolveTelemetry)
    assert tel.backend == backend
    assert tel.steps == s_on.last_supersteps
    assert tel.rows.shape[1] == SOLTEL_WIDTH
    if tel.steps:
        # a discharge ends with the last superstep doing something
        assert (tel.rows[:, 3] + tel.rows[:, 4]).max() > 0


@pytest.mark.parametrize("bucket", [(4, 40), (4, 130), (6, 300)], ids=str)
def test_layered_flows_bit_identical_on_off(bucket):
    C, M = bucket
    rng = np.random.default_rng(11)
    lp = LayeredProblem(
        supply=rng.integers(1, 30, C).astype(np.int32),
        col_cap=rng.integers(0, 3, M).astype(np.int32),
        cost_cm=rng.integers(0, 50, (C, M)).astype(np.int32),
        unsched_cost=40,
        ec_cost=2,
    )
    off = LayeredTransportSolver(telemetry=0)
    on = LayeredTransportSolver(telemetry=CAP)
    r_off = off.solve_layered(lp)
    r_on = on.solve_layered(lp)
    assert np.array_equal(r_on.y, r_off.y)
    assert r_on.objective == r_off.objective
    assert r_on.supersteps == r_off.supersteps
    if r_on.supersteps:
        tel = on.last_telemetry
        assert tel is not None and tel.backend == "layered"
        assert tel.steps == r_on.supersteps
    else:
        assert on.last_telemetry is None  # closed-form path: no loop ran


def test_jax_mega_telemetry_rows_identical():
    """jax and mega run the same algorithm superstep for superstep —
    their telemetry rows must agree exactly, not just their flows.
    mega clamps its ring to one VMEM tile (mega_telemetry_cap), so the
    comparison runs over the common tail of kept supersteps."""
    p = _problem(14, 4, seed=3)
    j = JaxSolver(telemetry=CAP)
    m = MegaSolver(interpret=True, telemetry=CAP)
    j.solve(p)
    m.solve(p)
    tj, tm = j.last_telemetry, m.last_telemetry
    assert tj.steps == tm.steps
    k = min(len(tj.rows), len(tm.rows))
    assert k > 0
    assert np.array_equal(tj.rows[-k:], tm.rows[-k:])


def test_disabled_module_resolves_cap_zero():
    prior = soltel.enabled()
    try:
        soltel.set_enabled(False)
        assert soltel.resolve_cap(None) == 0
        s = JaxSolver(telemetry=soltel.resolve_cap(None))
        s.solve(_problem(8, 3, seed=1))
        assert s.last_telemetry is None
        soltel.set_enabled(True)
        assert soltel.resolve_cap(None) == soltel.SOLTEL_DEFAULT_CAP
        assert soltel.resolve_cap(7) == 7
        assert soltel.resolve_cap(0) == 0  # explicit off overrides on
    finally:
        soltel.set_enabled(prior)


# ---------------------------------------------------------------------------
# 2. decode / explicit truncation
# ---------------------------------------------------------------------------


def test_decode_no_truncation():
    cap = 16
    buf = np.zeros((cap, SOLTEL_WIDTH), np.int32)
    for i in range(5):
        buf[i] = i + 1
    tel = decode(buf, steps=5, cap=cap, backend="t", budget=100)
    assert not tel.truncated and tel.start_step == 0
    assert tel.rows.shape == (5, SOLTEL_WIDTH)
    assert tel.rows[-1, 0] == 5


def test_decode_ring_truncation_is_explicit():
    cap = 8
    buf = np.zeros((cap, SOLTEL_WIDTH), np.int32)
    steps = 21  # rows 13..20 survive, at ring slots 13%8.. etc.
    for s in range(steps - cap, steps):
        buf[s % cap] = s
    tel = decode(buf, steps=steps, cap=cap, backend="t", budget=100)
    assert tel.truncated and tel.start_step == steps - cap
    assert list(tel.rows[:, 0]) == list(range(steps - cap, steps))


def test_solver_ring_keeps_final_supersteps():
    """A tiny ring on a real solve keeps exactly the last rows of the
    CAP-capacity recording — truncation loses the head, never the
    tail, and says so. (The CAP recording itself may be truncated; the
    tiny ring's rows must still be its exact suffix.)"""
    p = _problem(14, 4, seed=7)
    full = JaxSolver(telemetry=CAP)
    tiny = JaxSolver(telemetry=4)
    full.solve(p)
    tiny.solve(p)
    t_full, t_tiny = full.last_telemetry, tiny.last_telemetry
    assert t_full.steps == t_tiny.steps
    assert t_tiny.truncated == (t_tiny.steps > 4)
    assert np.array_equal(t_tiny.rows, t_full.rows[-len(t_tiny.rows):])
    assert t_tiny.start_step == t_full.steps - len(t_tiny.rows)


def test_decode_rejects_shape_mismatch():
    with pytest.raises(ValueError):
        decode(np.zeros((4, 3)), steps=2, cap=4, backend="t", budget=10)


# ---------------------------------------------------------------------------
# 3. stall detection
# ---------------------------------------------------------------------------


def _tel(rows, steps=None, budget=10_000, converged=True):
    rows = np.asarray(rows, np.int32)
    return SolveTelemetry(
        backend="t", steps=steps if steps is not None else len(rows),
        budget=budget, cap=len(rows), truncated=False, start_step=0,
        rows=rows, converged=converged,
    )


def _rows(n, eps=1, excess=5, active=2):
    r = np.zeros((n, SOLTEL_WIDTH), np.int32)
    r[:, 0] = eps
    r[:, 1] = active
    r[:, 2] = excess
    return r


def test_detect_excess_plateau():
    reason = detect_stall(_tel(_rows(64), converged=False), window=64)
    assert reason["kind"] == "excess_plateau"
    assert reason["window"] == 64 and reason["excess"] == 5


def test_detect_eps_plateau():
    rows = _rows(128, eps=64)
    rows[:, 2] = np.arange(128, 0, -1)  # excess IS decreasing (slowly)
    reason = detect_stall(_tel(rows, converged=False), window=64)
    assert reason["kind"] == "eps_plateau"


def test_detect_budget_exhausted():
    rows = _rows(8)
    rows[:, 2] = np.arange(8, 0, -1)
    reason = detect_stall(_tel(rows, steps=8, budget=8, converged=False))
    assert reason["kind"] == "superstep_budget_exhausted"


def test_detect_cap_proximity_on_converged_solve():
    rows = _rows(95)
    rows[:, 2] = np.arange(95, 0, -1)
    reason = detect_stall(_tel(rows, steps=95, budget=100, converged=True),
                          window=200)
    assert reason["kind"] == "superstep_cap_proximity"


def test_detect_nothing_on_healthy_solve():
    rows = _rows(10)
    rows[:, 2] = np.arange(10, 0, -1)
    assert detect_stall(_tel(rows, budget=10_000)) is None


def test_real_nonconvergence_raises_stall_error_with_telemetry():
    p = _problem(22, 5, seed=5)
    s = JaxSolver(max_supersteps=3, telemetry=CAP)
    with pytest.raises(SolverStallError) as ei:
        s.solve(p)
    err = ei.value
    assert isinstance(err, RuntimeError)  # ladder-absorbable
    assert err.telemetry is not None and err.telemetry.steps > 0
    assert not err.telemetry.converged
    assert err.reason is not None and err.reason["kind"] in (
        "superstep_budget_exhausted", "excess_plateau", "eps_plateau",
    )


# ---------------------------------------------------------------------------
# 4. ladder + flight integration
# ---------------------------------------------------------------------------


def test_ladder_failure_feeds_flight_dump(tmp_path):
    from ksched_tpu.obs.flight import FlightRecorder
    from ksched_tpu.runtime.degrade import DegradingSolver

    soltel.reset_stalls()
    with scoped_registry():
        p = _problem(22, 5, seed=5)
        # rung 0 cannot converge in 3 supersteps; rung 1 succeeds
        ladder = DegradingSolver([
            ("tiny", JaxSolver(max_supersteps=3, telemetry=CAP)),
            ("jax", JaxSolver(telemetry=CAP)),
        ])
        res = ladder.solve(p)
        assert res is not None and ladder.last_rung == 1
        assert ladder.last_failure_reasons, "no structured reason recorded"
        reason = ladder.last_failure_reasons[0]
        assert reason["rung"] == "tiny"
        assert reason["kind"] in (
            "superstep_budget_exhausted", "excess_plateau", "eps_plateau",
        )
        assert reason["telemetry_tail"], "no telemetry tail on the event"
        assert reason["telemetry_cols"] == list(SOLTEL_COLS)

        # the flight dump embeds the stall ring; the failed rung's
        # structured event is in it (the SUCCEEDING rung may also have
        # noted a converged-solve plateau warning — that's the tail
        # early-warning, not the failure)
        fr = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
        path = fr.dump("manual")
        import json

        dump = json.load(open(path))
        stalls = dump["solver_stalls"]
        rung_evs = [s for s in stalls if s.get("rung") == "tiny"]
        assert rung_evs and rung_evs[-1]["kind"] == reason["kind"]
        assert rung_evs[-1]["telemetry_tail"] == reason["telemetry_tail"]
        assert rung_evs[-1]["converged"] is False
    soltel.reset_stalls()


def test_failure_reason_classifies_injected_fault():
    reason = soltel.failure_reason("jax", RuntimeError("chaos: forced non-convergence"))
    assert reason["kind"] == "injected_fault"
    reason = soltel.failure_reason("jax", ValueError("non-finite arc costs"))
    assert reason["kind"] == "rejected_input"
    reason = soltel.failure_reason("jax", OverflowError("potentials"))
    assert reason["kind"] == "overflow"


# ---------------------------------------------------------------------------
# 5. publication: registry + synthesized child spans
# ---------------------------------------------------------------------------


def test_solve_traced_publishes_histograms_and_spans():
    from ksched_tpu.obs.spans import SpanTracer

    p = _problem(14, 4, seed=7)
    s = JaxSolver(telemetry=CAP)
    tracer = SpanTracer()
    with scoped_registry() as reg:
        with tracer:
            s.solve_traced(p)
        steps = s.last_supersteps
        assert reg.value("ksched_solve_supersteps", backend="jax") == 1
        assert reg.value("ksched_solve_pushes_total", backend="jax") > 0
        events = tracer.events()
        solve_ev = [e for e in events if e["name"] == "backend_solve"]
        steps_ev = [e for e in events if e["name"] == "superstep"]
        assert len(solve_ev) == 1
        assert len(steps_ev) == min(steps, CAP)
        # child spans sit INSIDE the backend_solve span and carry the
        # convergence args Perfetto shows
        parent = solve_ev[0]
        for ev in steps_ev:
            assert ev["args"]["parent_sid"] == parent["args"]["sid"]
            assert ev["ts"] >= parent["ts"] - 1e-6
            assert "eps" in ev["args"] and "active" in ev["args"]
        # steps are consecutive and end at the last superstep
        idx = [ev["args"]["step"] for ev in steps_ev]
        assert idx == list(range(steps - len(steps_ev), steps))


def test_publish_round_supersteps_device_path():
    with scoped_registry() as reg:
        soltel.publish_round_supersteps([3, 5, 9], backend="device/cpu")
        assert reg.value("ksched_solve_supersteps", backend="device/cpu") == 3


def test_publish_counts_truncation():
    with scoped_registry() as reg:
        rows = _rows(4)
        tel = SolveTelemetry(
            backend="t", steps=9, budget=100, cap=4, truncated=True,
            start_step=5, rows=rows,
        )
        soltel.publish(tel)
        assert reg.value("ksched_solve_telemetry_truncated_total", backend="t") == 1


def test_phases_split_on_eps_transitions():
    rows = np.zeros((7, SOLTEL_WIDTH), np.int32)
    rows[:, 0] = [64, 64, 8, 8, 8, 1, 1]
    tel = _tel(rows)
    assert tel.phases() == [
        {"eps": 64, "supersteps": 2},
        {"eps": 8, "supersteps": 3},
        {"eps": 1, "supersteps": 2},
    ]
