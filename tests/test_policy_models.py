"""Policy-model tests: Void, Random, Octopus, SJF, Quincy, Net.

Each model runs end-to-end through the real scheduler (graph build →
MCMF solve → delta apply) on a small synthetic cluster, and each test
asserts the policy's signature behavior — not just that it runs.
"""

import numpy as np
import pytest

from ksched_tpu.costmodels import (
    MODEL_REGISTRY,
    CostModelType,
    NetCostModel,
    OctopusCostModel,
    QuincyCostModel,
    RandomCostModel,
    SjfCostModel,
    VoidCostModel,
)
from ksched_tpu.data import ReferenceDescriptor, ReferenceType
from ksched_tpu.drivers import add_job, add_machine, build_cluster
from ksched_tpu.utils import resource_id_from_string, seed_rng


def _cluster(model_cls, machines=3, cores=1, pus=2, slots=1):
    return build_cluster(
        num_machines=machines,
        num_cores=cores,
        pus_per_core=pus,
        max_tasks_per_pu=slots,
        cost_model_factory=model_cls,
    )


def test_registry_covers_every_enumerated_model():
    assert set(MODEL_REGISTRY) == set(CostModelType)


@pytest.mark.parametrize("model_type", list(CostModelType))
def test_every_model_schedules_end_to_end(model_type):
    sched, rmap, jmap, tmap, root = _cluster(MODEL_REGISTRY[model_type])
    add_job(sched, jmap, tmap, num_tasks=4)
    n, deltas = sched.schedule_all_jobs()
    # Void legitimately may place nothing (all-zero costs); everyone else
    # must fill the demand.
    if model_type != CostModelType.VOID:
        assert n == 4, f"{model_type.name} placed {n}/4"
    assert sched.gm.sink_node.excess == -len(sched.gm.task_to_node)


def test_random_is_reproducible_under_seed():
    def run():
        seed_rng(123)
        sched, rmap, jmap, tmap, root = _cluster(RandomCostModel)
        add_job(sched, jmap, tmap, num_tasks=4)
        sched.schedule_all_jobs()
        return sorted(sched.get_task_bindings().values())

    assert run() == run()


def test_octopus_balances_load():
    # 4 machines x 2 PUs; tasks arrive one per round. Octopus prices a
    # machine by its observed load (stats refresh between rounds — the
    # model is load-reactive, like Firmament's octopus), so each arrival
    # must land on a still-idle machine: 1 task per machine, not packed.
    sched, rmap, jmap, tmap, root = _cluster(OctopusCostModel, machines=4, pus=2)
    n = 0
    for _ in range(4):
        add_job(sched, jmap, tmap, num_tasks=1)
        placed, _ = sched.schedule_all_jobs()
        n += placed
    assert n == 4
    # map bound PUs -> machine: count tasks per machine
    per_machine = {}
    for t, pu_rid in sched.get_task_bindings().items():
        rs = rmap.find(pu_rid)
        # walk up to the machine via parent ids
        node = rs.topology_node
        while node.resource_desc.type.name != "MACHINE":
            parent_rid = resource_id_from_string(node.parent_id)
            node = rmap.find(parent_rid).topology_node
        per_machine[node.resource_desc.uuid] = per_machine.get(node.resource_desc.uuid, 0) + 1
    assert max(per_machine.values()) == 1, f"octopus packed: {per_machine}"


def test_sjf_prioritizes_short_jobs_under_contention():
    # 1 machine x 2 slots; short job (2 tasks) + long job (2 tasks).
    sched, rmap, jmap, tmap, root = _cluster(SjfCostModel, machines=1, pus=2)
    short_job = add_job(sched, jmap, tmap, num_tasks=2)
    long_job = add_job(sched, jmap, tmap, num_tasks=2)
    model: SjfCostModel = sched.cost_model
    model.record_completion(str(short_job), 10.0)
    model.record_completion(str(long_job), 9000.0)
    n, _ = sched.schedule_all_jobs()
    assert n == 2  # only two slots
    placed = set(sched.get_task_bindings().keys())
    short_tasks = {t for t, td in tmap.items() if td.job_id == str(short_job)}
    assert placed == short_tasks, "SJF must give contended slots to the short job"


def test_quincy_prefers_data_local_machine():
    sched, rmap, jmap, tmap, root = _cluster(QuincyCostModel, machines=3, pus=2)
    model: QuincyCostModel = sched.cost_model
    machines = list(model._machines.keys())
    target = machines[1]
    job = add_job(sched, jmap, tmap, num_tasks=1)
    (task_id,) = [t for t, td in tmap.items() if td.job_id == str(job)]
    td = tmap.find(task_id)
    # task reads one 512 MB block that lives on machine[1]
    td.dependencies.append(
        ReferenceDescriptor(id=77, type=ReferenceType.CONCRETE, size=512 << 20)
    )
    model.blocks.register(77, 512 << 20, [target])
    assert model.get_task_preference_arcs(task_id) == [target]
    n, _ = sched.schedule_all_jobs()
    assert n == 1
    (pu_rid,) = sched.get_task_bindings().values()
    node = rmap.find(pu_rid).topology_node
    while node.resource_desc.type.name != "MACHINE":
        node = rmap.find(resource_id_from_string(node.parent_id)).topology_node
    assert resource_id_from_string(node.resource_desc.uuid) == target


def test_quincy_wait_cost_grows():
    sched, rmap, jmap, tmap, root = _cluster(QuincyCostModel, machines=1, pus=1)
    model: QuincyCostModel = sched.cost_model
    model.add_task(42)
    c0 = model.task_to_unscheduled_agg_cost(42)
    model.note_round([42])
    model.note_round([42])
    assert model.task_to_unscheduled_agg_cost(42) > c0


def test_net_gates_machines_without_bandwidth():
    sched, rmap, jmap, tmap, root = _cluster(NetCostModel, machines=2, pus=2)
    model: NetCostModel = sched.cost_model
    machines = list(model._machines.keys())
    # The GATED machine comes first in arc order so a tie-break cannot
    # mask a broken gate; the roomy machine is second.
    rmap.find(machines[0]).descriptor.capacity.net_bw = 1
    rmap.find(machines[1]).descriptor.capacity.net_bw = 100
    machines = [machines[1]]  # expected landing spot
    job = add_job(sched, jmap, tmap, num_tasks=2)
    for t, td in tmap.items():
        if td.job_id == str(job):
            td.resource_request.net_bw = 40
    n, _ = sched.schedule_all_jobs()
    assert n == 2
    # both tasks must land on the machine that can fit 40 bw each
    for t, pu_rid in sched.get_task_bindings().items():
        node = rmap.find(pu_rid).topology_node
        while node.resource_desc.type.name != "MACHINE":
            node = rmap.find(resource_id_from_string(node.parent_id)).topology_node
        assert resource_id_from_string(node.resource_desc.uuid) == machines[0]


def test_net_leaves_unfittable_task_unscheduled():
    # Request 50 exceeds every machine's bandwidth: the unsched escape
    # (cheaper than the gate) must win — no overcommitted placement.
    sched, rmap, jmap, tmap, root = _cluster(NetCostModel, machines=2, pus=2)
    model: NetCostModel = sched.cost_model
    for m in model._machines:
        rmap.find(m).descriptor.capacity.net_bw = 10
    job = add_job(sched, jmap, tmap, num_tasks=1)
    for t, td in tmap.items():
        if td.job_id == str(job):
            td.resource_request.net_bw = 50
    n, _ = sched.schedule_all_jobs()
    assert n == 0
    assert sched.get_task_bindings() == {}


def test_void_keeps_supply_conserved():
    sched, rmap, jmap, tmap, root = _cluster(VoidCostModel)
    add_job(sched, jmap, tmap, num_tasks=3)
    sched.schedule_all_jobs()
    assert sched.gm.sink_node.excess == -len(sched.gm.task_to_node)
