"""JAX push-relabel solver: parity vs the exact CPU oracle.

MCMF optima are non-unique, so parity = identical objective cost (the
well-defined invariant); scheduler-level placement parity is asserted in
test_scheduler_backends.py under a deterministic tie-break.
"""

import numpy as np
import pytest

from ksched_tpu.graph.device_export import FlowProblem
from ksched_tpu.solver import ReferenceSolver
from ksched_tpu.solver.jax_solver import JaxSolver

from test_solver_oracle import make_problem


def assert_valid_flow(p: FlowProblem, flow: np.ndarray):
    assert (flow >= 0).all() and (flow <= p.cap).all()
    n = p.num_nodes
    out_ = np.zeros(n, np.int64)
    in_ = np.zeros(n, np.int64)
    np.add.at(out_, p.src, flow)
    np.add.at(in_, p.dst, flow)
    assert ((p.excess - out_ + in_) == 0).all()


@pytest.mark.parametrize("case", ["single", "cheap", "split", "assign", "escape"])
def test_small_parity(case):
    problems = {
        "single": make_problem(4, {1: 1, 3: -1}, [(1, 2, 0, 1, 2), (2, 3, 0, 1, 3)]),
        "cheap": make_problem(
            4, {1: 1, 3: -1}, [(1, 3, 0, 1, 10), (1, 2, 0, 1, 2), (2, 3, 0, 1, 3)]
        ),
        "split": make_problem(
            4, {1: 2, 3: -2}, [(1, 3, 0, 9, 10), (1, 2, 0, 1, 2), (2, 3, 0, 9, 3)]
        ),
        "assign": make_problem(
            8,
            {1: 1, 2: 1, 6: -2},
            [
                (1, 3, 0, 1, 2),
                (2, 3, 0, 1, 2),
                (3, 4, 0, 1, 0),
                (3, 5, 0, 1, 4),
                (4, 6, 0, 1, 0),
                (5, 6, 0, 1, 0),
                (1, 7, 0, 1, 50),
                (2, 7, 0, 1, 50),
                (7, 6, 0, 2, 0),
            ],
        ),
        "escape": make_problem(
            8,
            {1: 1, 2: 1, 6: -2},
            [
                (1, 3, 0, 1, 2),
                (2, 3, 0, 1, 2),
                (3, 4, 0, 1, 0),
                (4, 6, 0, 1, 0),
                (1, 7, 0, 1, 5),
                (2, 7, 0, 1, 5),
                (7, 6, 0, 2, 0),
            ],
        ),
    }
    p = problems[case]
    ref = ReferenceSolver().solve(p)
    jx = JaxSolver().solve(p)
    assert_valid_flow(p, jx.flow)
    assert jx.objective == ref.objective


def random_scheduling_problem(rng, num_tasks, num_machines, slots_per_machine, num_jobs=3):
    """Build a random quincy-style layered instance directly in arrays:
    tasks -> (unsched | EC) ; EC -> machines ; machine -> PUs ; PU -> sink."""
    # node ids: 1..T tasks, then EC, then machines, PUs, unscheds, sink
    nid = 1
    tasks = list(range(nid, nid + num_tasks)); nid += num_tasks
    ec = nid; nid += 1
    machines = list(range(nid, nid + num_machines)); nid += num_machines
    pus = []
    for _ in range(num_machines):
        pus.append(list(range(nid, nid + slots_per_machine)))
        nid += slots_per_machine
    unscheds = list(range(nid, nid + num_jobs)); nid += num_jobs
    sink = nid; nid += 1

    arcs = []
    excess = {}
    for i, t in enumerate(tasks):
        excess[t] = 1
        job = i % num_jobs
        arcs.append((t, unscheds[job], 0, 1, int(rng.integers(3, 10))))
        arcs.append((t, ec, 0, 1, int(rng.integers(0, 5))))
        # occasional direct preference arc to a machine
        if rng.random() < 0.3:
            m = int(rng.integers(0, num_machines))
            arcs.append((t, machines[m], 0, 1, int(rng.integers(0, 3))))
    for m in range(num_machines):
        arcs.append((ec, machines[m], 0, slots_per_machine, int(rng.integers(0, 4))))
        for pu in pus[m]:
            arcs.append((machines[m], pu, 0, 1, 0))
            arcs.append((pu, sink, 0, 1, 0))
    for u in unscheds:
        arcs.append((u, sink, 0, num_tasks, 0))
    excess[sink] = -num_tasks
    return make_problem(nid, excess, arcs)


def test_random_parity():
    rng = np.random.default_rng(0)
    for trial in range(8):
        p = random_scheduling_problem(
            rng,
            num_tasks=int(rng.integers(3, 25)),
            num_machines=int(rng.integers(1, 6)),
            slots_per_machine=int(rng.integers(1, 4)),
        )
        ref = ReferenceSolver().solve(p)
        jx = JaxSolver().solve(p)
        assert jx.objective == ref.objective, f"trial {trial}"
        assert_valid_flow(p, jx.flow)


def test_warm_start_incremental():
    rng = np.random.default_rng(1)
    p = random_scheduling_problem(rng, num_tasks=10, num_machines=3, slots_per_machine=2)
    solver = JaxSolver()
    r1 = solver.solve(p)
    ref1 = ReferenceSolver().solve(p)
    assert r1.objective == ref1.objective
    cold_steps = solver.last_supersteps

    # Perturb: raise one unsched cost and re-solve warm.
    p2 = FlowProblem(
        num_nodes=p.num_nodes,
        excess=p.excess.copy(),
        node_type=p.node_type,
        src=p.src,
        dst=p.dst,
        cap=p.cap.copy(),
        cost=p.cost.copy(),
        flow_offset=p.flow_offset,
        num_arcs=p.num_arcs,
    )
    p2.cost[0] += 2
    r2 = solver.solve(p2)
    ref2 = ReferenceSolver().solve(p2)
    assert r2.objective == ref2.objective
    # warm restart should not be wildly more expensive than cold
    assert solver.last_supersteps <= max(cold_steps * 2, 50)
