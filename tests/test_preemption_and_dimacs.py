"""Coverage for reference-behavior axes the suite didn't yet pin down:

- the preemption flag's two effects (pin-vs-keep task arcs,
  graph_manager.go:675-720 vs :855-888; the capacity-to-parent rule,
  :662-667) and preemption deltas (:297-339);
- task migration deltas (MIGRATE when bound elsewhere, :253-295);
- the DIMACS wire format (doc.go:3-22; solver-side node taxonomy
  export.go:53-70; incremental lines + "c EOI" framing export.go:28-37);
- EC purge and job completion (graph_manager.go:341-357).
"""

import io

from ksched_tpu.data import DeltaType
from ksched_tpu.drivers import add_job, build_cluster
from ksched_tpu.graph.changes import ChangeManager, ChangeType
from ksched_tpu.graph.dimacs import export, export_incremental, parse_graph
from ksched_tpu.graph.flowgraph import ArcType, NodeType


# ---------------------------------------------------------------------------
# preemption semantics
# ---------------------------------------------------------------------------


def _bound_task_nodes(sched):
    return [
        sched.gm.task_to_node[tid]
        for tid in sched.task_bindings
    ]


def test_preemption_off_pins_scheduled_tasks():
    """Without preemption a placed task keeps exactly one outgoing arc:
    the running arc, lower bound 1 (graph_manager.go:675-720)."""
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=2, pus_per_core=2)
    add_job(sched, jmap, tmap, num_tasks=3)
    n, _ = sched.schedule_all_jobs()
    assert n == 3
    for node in _bound_task_nodes(sched):
        arcs = list(node.outgoing.values())
        assert len(arcs) == 1
        assert arcs[0].type == ArcType.RUNNING
        assert arcs[0].cap_lower == 1


def test_preemption_on_keeps_unscheduled_escape_arc():
    """With preemption every placed task keeps its unsched escape arc
    (priced as preemption cost) next to the running arc
    (graph_manager.go:855-888, :1164-1181)."""
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=2, pus_per_core=2, preemption=True
    )
    add_job(sched, jmap, tmap, num_tasks=3)
    n, _ = sched.schedule_all_jobs()
    assert n == 3
    for node in _bound_task_nodes(sched):
        arcs = list(node.outgoing.values())
        kinds = sorted(a.type for a in arcs)
        assert ArcType.RUNNING in kinds
        unsched_arcs = [
            a for a in arcs if a.dst_node.type == NodeType.JOB_AGGREGATOR
        ]
        assert len(unsched_arcs) == 1
        assert unsched_arcs[0].cap_lower == 0  # escape stays optional


def test_capacity_rule_flips_with_preemption():
    """capacityFromResNodeToParent: slots-below minus running-below when
    preemption is off, slots-below when on (graph_manager.go:662-667)."""
    results = {}
    for flag in (False, True):
        sched, rmap, jmap, tmap, root = build_cluster(
            num_machines=1, pus_per_core=2, preemption=flag
        )
        add_job(sched, jmap, tmap, num_tasks=2)
        sched.schedule_all_jobs()
        # running-task stats reconcile on the NEXT round's topology
        # refresh (reference-parity lag; flowscheduler/scheduler.go:375)
        sched.schedule_all_jobs()
        machine_node = next(
            node
            for node in sched.gm.resource_to_node.values()
            if node.type == NodeType.MACHINE
        )
        parent = sched.gm.node_to_parent_node[machine_node.id]
        arc = sched.gm.cm.graph.get_arc(parent, machine_node)
        results[flag] = arc.cap_upper
    assert results[False] == 0  # both slots occupied, not reclaimable
    assert results[True] == 2  # preemption can reclaim them


def test_preempt_delta_emitted_for_vanished_mapping():
    """A running task absent from the new solver mapping becomes a
    PREEMPT delta and its slot frees (graph_manager.go:297-339)."""
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=1, pus_per_core=1, preemption=True
    )
    add_job(sched, jmap, tmap, num_tasks=1)
    n, _ = sched.schedule_all_jobs()
    assert n == 1
    (tid,) = list(sched.task_bindings)
    deltas = sched.gm.scheduling_deltas_for_preempted_tasks({}, rmap)
    assert [d.type for d in deltas] == [DeltaType.PREEMPT]
    assert deltas[0].task_id == tid


def test_migration_rebinds_task():
    """MIGRATE: binding moves, old slot frees, new slot fills
    (flowscheduler/scheduler.go:248-270)."""
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=2, pus_per_core=1)
    add_job(sched, jmap, tmap, num_tasks=1)
    n, _ = sched.schedule_all_jobs()
    assert n == 1
    (tid,) = list(sched.task_bindings)
    old_rid = sched.task_bindings[tid]
    # the other machine's PU
    other = next(
        rid
        for rid, node in sched.gm.resource_to_node.items()
        if node.type == NodeType.PU and rid != old_rid
    )
    td = tmap.find(tid)
    rs = rmap.find(other)
    sched.handle_task_migration(td, rs.descriptor)
    assert sched.task_bindings[tid] == other
    assert tid in rs.descriptor.current_running_tasks


# ---------------------------------------------------------------------------
# DIMACS wire format (golden)
# ---------------------------------------------------------------------------


def _tiny_graph():
    cm = ChangeManager()
    sink = cm.add_node(NodeType.SINK, 0, ChangeType.ADD_SINK_NODE, "SINK")
    task = cm.add_node(NodeType.UNSCHEDULED_TASK, 1, ChangeType.ADD_TASK_NODE, "t")
    sink.excess = -1  # the graph manager's supply bookkeeping
    pu = cm.add_node(NodeType.PU, 0, ChangeType.ADD_RESOURCE_NODE, "pu")
    cm.add_arc(task, pu, 0, 1, 42, ArcType.OTHER, ChangeType.ADD_ARC_TASK_TO_RES, "t->pu")
    cm.add_arc(pu, sink, 0, 1, 0, ArcType.OTHER, ChangeType.ADD_ARC_RES_TO_SINK, "pu->sink")
    return cm, sink, task, pu


def test_dimacs_full_export_golden():
    cm, sink, task, pu = _tiny_graph()
    buf = io.StringIO()
    export(cm.graph, buf)
    text = buf.getvalue()
    lines = text.strip().splitlines()
    assert lines[-1] == "c EOI"
    header, nodes, arcs = parse_graph(lines)
    assert header == (3, 2)
    # solver-side taxonomy: task=1, PU=2, sink=3 (export.go:53-70)
    by_id = {n[0]: n for n in nodes}
    assert by_id[task.id][1:] == (1, 1)   # excess 1, type task
    assert by_id[pu.id][1:] == (0, 2)     # type PU
    assert by_id[sink.id][1:] == (-1, 3)  # absorbed supply, type sink
    assert (task.id, pu.id, 0, 1, 42) in arcs
    assert (pu.id, sink.id, 0, 1, 0) in arcs


def test_dimacs_incremental_export_golden():
    cm, sink, task, pu = _tiny_graph()
    cm.reset_changes()
    arc = cm.graph.get_arc(task, pu)
    cm.change_arc_cost(arc, 7, ChangeType.CHG_ARC_TASK_TO_RES, "reprice")
    cm.delete_arc(
        cm.graph.get_arc(pu, sink), ChangeType.DEL_ARC_BETWEEN_RES, "drop"
    )
    buf = io.StringIO()
    export_incremental(cm.get_graph_changes(), buf)
    lines = buf.getvalue().strip().splitlines()
    assert lines[-1] == "c EOI"
    body = [l for l in lines if not l.startswith("c")]
    # reprice first: update-arc line carries old cost last
    # (update_arc_change.go:46-54); delete = capacity-to-zero update
    # (graph_change_manager.go:184-193).
    assert body[0].startswith(f"x {task.id} {pu.id} 0 1 7")
    assert body[0].endswith("42")
    assert any(
        l.startswith(f"x {pu.id} {sink.id} 0 0 0") for l in body[1:]
    )


# ---------------------------------------------------------------------------
# EC purge + job completion
# ---------------------------------------------------------------------------


def test_purge_unconnected_equiv_class_nodes():
    """The per-round purge (beyond-parity: the reference declares the
    API but never calls it, graph_manager.go:347-357; upstream
    Firmament purges in its loop) removes the cluster-agg EC once every
    task is pinned; a waiting task keeps it alive."""
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=1, pus_per_core=1)
    add_job(sched, jmap, tmap, num_tasks=2)  # 1 slot: one pins, one waits
    sched.schedule_all_jobs()
    # the waiting task's EC arc keeps the aggregator connected
    assert sched.gm.task_ec_to_node
    (tid,) = list(sched.task_bindings)
    sched.handle_task_completion(tmap.find(tid))
    sched.schedule_all_jobs()  # sees pre-completion stats (1-round lag)
    sched.schedule_all_jobs()  # places + pins the waiter
    assert len(sched.task_bindings) == 1
    # everyone pinned -> the round's purge marked the idle EC
    # (debounce); a second observation removes it
    sched.gm.purge_unconnected_equiv_class_nodes()
    assert not sched.gm.task_ec_to_node


def test_job_completion_removes_unsched_aggregator():
    sched, rmap, jmap, tmap, root = build_cluster(num_machines=1, pus_per_core=2)
    jid = add_job(sched, jmap, tmap, num_tasks=2)
    n, _ = sched.schedule_all_jobs()
    assert n == 2
    for tid in list(sched.task_bindings):
        sched.handle_task_completion(tmap.find(tid))
    sched.handle_job_completion(jid)
    assert not sched.gm.job_unsched_to_node
    # supply conservation after full teardown
    assert sched.gm.sink_node.excess == -len(sched.gm.task_to_node) == 0


# ---------------------------------------------------------------------------
# solver flow-response codec (the loop back from an external solver)
# ---------------------------------------------------------------------------


def test_flow_response_round_trip_matches_in_process_decode():
    """export_flow -> parse_flow -> flow_on_arcs -> flow_to_mapping must
    reproduce the in-process decode exactly, closing the DIMACS loop so
    an external solver (e.g. real Flowlessly) can serve as a parity
    oracle (response format: placement/solver.go:134-179)."""
    from ksched_tpu.graph.dimacs import export_flow, flow_on_arcs, parse_flow
    from ksched_tpu.solver.decode import flow_to_mapping

    sched, rmap, jmap, tmap, root = build_cluster(num_machines=2, pus_per_core=2)
    add_job(sched, jmap, tmap, num_tasks=3)
    n, _ = sched.schedule_all_jobs()
    assert n == 3

    ps = sched.solver
    problem = ps.state.problem()
    result = ps.backend.solve(problem)
    tf = result.total_flow(problem)
    assert tf.sum() > 0
    task_ids = [node.id for node in sched.gm.task_to_node.values()]
    direct = flow_to_mapping(
        problem, tf, sched.gm.leaf_node_ids, sched.gm.sink_node.id, task_ids
    )
    assert direct  # placements exist

    buf = io.StringIO()
    export_flow(problem.src, problem.dst, tf, buf)
    text = buf.getvalue()
    assert text.endswith("c EOI\n")
    # prepend the solver's timing chatter the reference skips
    # (solver.go:169-170) and trailing garbage the EOI framing must hide
    wire = "c ALGORITHM successive_shortest_path 12ms\n" + text + "f 9 9 9\n"
    flows = parse_flow(io.StringIO(wire))
    assert (9, 9) not in flows  # post-EOI lines belong to the next round
    tf2 = flow_on_arcs(flows, problem.src, problem.dst)
    assert (tf2 == tf).all()
    external = flow_to_mapping(
        problem, tf2, sched.gm.leaf_node_ids, sched.gm.sink_node.id, task_ids
    )
    assert external == direct


def test_parse_flow_last_pair_wins_and_rejects_junk():
    from ksched_tpu.graph.dimacs import parse_flow

    flows = parse_flow(io.StringIO("f 1 2 3\nf 1 2 5\nc EOI\n"))
    assert flows == {(1, 2): 5}
    try:
        parse_flow(io.StringIO("q nonsense\n"))
    except ValueError:
        pass
    else:
        raise AssertionError("junk line must raise")
    try:
        parse_flow(io.StringIO("f 1 2 3\n"))  # dead solver / cut pipe
    except ValueError:
        pass
    else:
        raise AssertionError("truncated response (no c EOI) must raise")
