"""Device-path trace replay (drivers/trace_replay.py
DeviceTraceReplayDriver + DeviceBulkCluster.run_replay_rounds): the
scanned replay program must be BIT-IDENTICAL to driving the same
cluster through the same windows one host call at a time — admissions,
completions, machine toggles, and rounds all agree — and the staging
host mirror must predict device row assignment exactly."""

import numpy as np

from ksched_tpu.drivers.trace_replay import (
    FAIL,
    FINISH,
    SUBMIT,
    DeviceTraceReplayDriver,
    TraceMachineEvent,
    TraceTaskEvent,
    synthesize_trace,
)
from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster


def _small_trace(machine_churn=0.0, seed=3):
    return synthesize_trace(
        num_machines=12, num_tasks=120, duration_s=120.0,
        mean_runtime_s=30.0, seed=seed, machine_churn=machine_churn,
    )


def _host_driven_twin(driver, schedule):
    """Replay the staged windows against an identical cluster via the
    one-call-per-event host API; returns (cluster, per-round placed)."""
    import jax.numpy as jnp

    d = driver.cluster
    twin = DeviceBulkCluster(
        num_machines=d.M, pus_per_machine=d.P, slots_per_pu=d.S,
        num_jobs=d.J, num_task_classes=d.C, task_capacity=d.Tcap,
        ec_cost=d.ec_cost, job_unsched_cost=d.job_unsched_cost,
        unsched_cost=d.unsched_cost, class_cost_fn=d.class_cost_fn,
        supersteps=d.supersteps if d.class_cost_fn is not None else None,
        decode_width=None,
    )
    twin.state = twin.state._replace(
        machine_enabled=jnp.zeros(d.M, jnp.bool_)
    )
    placed = []
    for i in range(schedule["rounds"]):
        for j in range(schedule["tog_n"][i]):
            twin.set_machine_enabled(
                int(schedule["tog_idx"][i, j]), bool(schedule["tog_on"][i, j])
            )
        dn = int(schedule["done_n"][i])
        if dn:
            twin.complete_tasks(schedule["done_rows"][i, :dn])
        an = int(schedule["adm_n"][i])
        twin.add_tasks(
            an, schedule["adm_job"][i, :an], schedule["adm_cls"][i, :an]
        )
        s = twin.fetch_stats(twin.round())
        assert bool(s["converged"])
        placed.append(int(s["placed"]))
    return twin, placed


def test_replay_scan_matches_host_driven_rounds():
    machines, events = _small_trace()
    driver = DeviceTraceReplayDriver(
        machines, slots_per_machine=2, num_jobs_hint=8,
        task_capacity=256, decode_width=None,
    )
    schedule = driver.stage(events, window_s=10.0)
    assert schedule["rounds"] >= 5
    assert schedule["submitted"] > 0 and schedule["finished"] > 0
    assert schedule["dropped"] == 0

    stats = driver.cluster.fetch_stats(driver.replay(schedule))
    assert stats["converged"].all()
    twin, twin_placed = _host_driven_twin(driver, schedule)

    assert stats["placed"].tolist() == twin_placed
    a = driver.cluster.fetch_state()
    b = twin.fetch_state()
    for k in a:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


def test_replay_scan_machine_churn_evicts_and_reschedules():
    machines, events = _small_trace(machine_churn=0.5, seed=9)
    driver = DeviceTraceReplayDriver(
        machines, slots_per_machine=2, num_jobs_hint=8,
        task_capacity=256, decode_width=None,
    )
    schedule = driver.stage(events, window_s=10.0)
    stats = driver.cluster.fetch_stats(driver.replay(schedule))
    assert stats["converged"].all()
    assert int(stats["evicted"].sum()) > 0, "churned trace must evict"

    # final-state consistency: occupancy recount matches, no task on a
    # disabled machine, and live == admitted - completed
    st = {k: np.asarray(v) for k, v in driver.cluster.fetch_state().items()}
    live, pu = st["live"], st["pu"]
    d = driver.cluster
    recount = np.bincount(pu[live & (pu >= 0)], minlength=d.num_pus)
    assert (recount == st["pu_running"]).all()
    on = live & (pu >= 0)
    machine_of = np.clip(pu, 0, d.num_pus - 1) // d.P
    assert st["machine_enabled"][machine_of[on]].all()
    assert int(live.sum()) == int(
        stats["admitted"].sum() - stats["completed"].sum()
    )

    # parity with the host-driven twin under churn too
    twin, twin_placed = _host_driven_twin(driver, schedule)
    assert stats["placed"].tolist() == twin_placed


def test_same_window_submit_finish_defers_not_leaks():
    """A task submitted AND finished inside one window cannot complete
    in that device round (completions precede admissions); its finish
    must defer one window — never silently drop, which would leak the
    row as live forever."""
    machines, events = synthesize_trace(
        num_machines=6, num_tasks=80, duration_s=120.0,
        mean_runtime_s=2.0,  # << window: most tasks finish same-window
        seed=7,
    )
    driver = DeviceTraceReplayDriver(
        machines, slots_per_machine=4, num_jobs_hint=4,
        task_capacity=128, decode_width=None,
    )
    schedule = driver.stage(events, window_s=30.0)
    assert schedule["dropped"] == 0
    # every submitted task must eventually be completed
    assert schedule["finished"] == schedule["submitted"] == 80
    stats = driver.cluster.fetch_stats(driver.replay(schedule))
    assert stats["converged"].all()
    assert int(stats["admitted"].sum()) == 80
    assert int(stats["completed"].sum()) == 80
    st = {k: np.asarray(v) for k, v in driver.cluster.fetch_state().items()}
    assert int(st["live"].sum()) == 0, "rows leaked live after the trace"


def test_duplicate_submit_skipped_not_leaked():
    """A duplicate SUBMIT for a live (job, task) — real Google-trace
    segments contain submit->FAIL->resubmit — must be SKIPPED (the
    reference's duplicate-pod skip, cmd/k8sscheduler/scheduler.go:
    133-136), not admitted again: overwriting the row mapping would
    orphan the first row live forever. A FAIL followed by a resubmit
    in a later batch must retire the old row and admit a fresh one."""
    machines = [TraceMachineEvent(0, 0, 0, cpus=4.0)]
    us = int(1e6)
    events = [
        TraceTaskEvent(0, 1, 0, SUBMIT),
        # window 2: duplicate SUBMIT while (1, 0) is still live
        TraceTaskEvent(6 * us, 1, 0, SUBMIT),
        # window 3: FAIL + resubmit batched together, then a final FINISH
        TraceTaskEvent(12 * us, 1, 0, FAIL),
        TraceTaskEvent(13 * us, 1, 0, SUBMIT),
        TraceTaskEvent(18 * us, 1, 0, FINISH),
    ]
    driver = DeviceTraceReplayDriver(
        machines, slots_per_machine=4, num_jobs_hint=2,
        task_capacity=16, decode_width=None,
    )
    schedule = driver.stage(events, window_s=5.0)
    # original admit + post-FAIL resubmit; the live-duplicate skipped
    assert schedule["submitted"] == 2
    assert schedule["finished"] == 2  # the FAIL and the FINISH
    assert schedule["dropped"] == 0
    stats = driver.cluster.fetch_stats(driver.replay(schedule))
    assert stats["converged"].all()
    assert int(stats["admitted"].sum()) == 2
    assert int(stats["completed"].sum()) == 2
    st = {k: np.asarray(v) for k, v in driver.cluster.fetch_state().items()}
    assert int(st["live"].sum()) == 0, "duplicate SUBMIT leaked a row"

    # the host driver agrees on the same stream
    from ksched_tpu.drivers.trace_replay import TraceReplayDriver

    host = TraceReplayDriver(machines, slots_per_machine=4, num_jobs_hint=2)
    hs = host.replay(events, window_s=5.0)
    assert hs.submitted == 2 and hs.finished == 2
    assert not host._live_tasks, "host driver leaked a live task"

    # FAIL + resubmit + FINISH all batched into ONE window: the first
    # finish retires the window-start row, the resubmit admits a fresh
    # one, and the second finish must target THAT row — not be consumed
    # as a duplicate of the first (which would leak the new row).
    events2 = [
        TraceTaskEvent(0, 1, 0, SUBMIT),
        TraceTaskEvent(6 * us, 1, 0, FAIL),
        TraceTaskEvent(7 * us, 1, 0, SUBMIT),
        TraceTaskEvent(9 * us, 1, 0, FINISH),
    ]
    d2 = DeviceTraceReplayDriver(
        machines, slots_per_machine=4, num_jobs_hint=2,
        task_capacity=16, decode_width=None,
    )
    sch2 = d2.stage(events2, window_s=5.0)
    assert sch2["submitted"] == 2 and sch2["finished"] == 2
    st2 = d2.cluster.fetch_stats(d2.replay(sch2))
    assert int(st2["completed"].sum()) == 2
    assert int(np.asarray(d2.cluster.fetch_state()["live"]).sum()) == 0
    h2 = TraceReplayDriver(machines, slots_per_machine=4, num_jobs_hint=2)
    hs2 = h2.replay(events2, window_s=5.0)
    assert hs2.submitted == 2 and hs2.finished == 2
    assert not h2._live_tasks, "same-window FAIL+resubmit+FINISH leaked"


def test_intra_window_interleavings_exact():
    """The shared window_net_ops automaton must replay intra-window
    event order exactly — the r4 review's two adversarial shapes:
    (a) duplicate SUBMIT then FINISH in one window (task must end DEAD:
    the dup is skipped, the finish retires the original row);
    (b) SUBMIT, FAIL, re-SUBMIT in one window (task must end LIVE, and
    a later FINISH must retire it)."""
    from ksched_tpu.drivers.trace_replay import TraceReplayDriver

    machines = [TraceMachineEvent(0, 0, 0, cpus=4.0)]
    us = int(1e6)

    # (a) live task; then [dup-SUBMIT, FINISH] in window 2
    events_a = [
        TraceTaskEvent(0, 1, 0, SUBMIT),
        TraceTaskEvent(6 * us, 1, 0, SUBMIT),  # dup while live
        TraceTaskEvent(7 * us, 1, 0, FINISH),  # retires the ORIGINAL
    ]
    # (b) [SUBMIT, FAIL, re-SUBMIT] all in window 1, FINISH later
    events_b = [
        TraceTaskEvent(0, 2, 0, SUBMIT),
        TraceTaskEvent(1 * us, 2, 0, FAIL),
        TraceTaskEvent(2 * us, 2, 0, SUBMIT),  # legitimate resubmit
        TraceTaskEvent(9 * us, 2, 0, FINISH),
    ]
    for events, n_sub, n_fin in [(events_a, 1, 1), (events_b, 2, 2)]:
        d = DeviceTraceReplayDriver(
            machines, slots_per_machine=4, num_jobs_hint=4,
            task_capacity=16, decode_width=None,
        )
        sch = d.stage(events, window_s=5.0)
        assert (sch["submitted"], sch["finished"]) == (n_sub, n_fin), events
        st = d.cluster.fetch_stats(d.replay(sch))
        assert int(st["completed"].sum()) == n_fin
        assert int(np.asarray(d.cluster.fetch_state()["live"]).sum()) == 0
        h = TraceReplayDriver(machines, slots_per_machine=4, num_jobs_hint=4)
        hs = h.replay(events, window_s=5.0)
        assert (hs.submitted, hs.finished) == (n_sub, n_fin), events
        assert not h._live_tasks


def test_stage_mirror_reuses_freed_rows():
    """A task that finishes frees its row for a later submit — the
    mirror must hand the row out again and completions must target the
    right (new) owner."""
    machines, events = _small_trace(seed=5)
    driver = DeviceTraceReplayDriver(
        machines, slots_per_machine=2, num_jobs_hint=8,
        task_capacity=64,  # tight pool forces reuse
        decode_width=None,
    )
    schedule = driver.stage(events, window_s=10.0)
    assert schedule["dropped"] == 0
    # 120 tasks streamed through a 64-row pool: rows MUST be reused,
    # and every completion must still land on its (current) owner
    assert schedule["submitted"] > 64
    stats = driver.cluster.fetch_stats(driver.replay(schedule))
    assert stats["converged"].all()
    assert int(stats["admitted"].sum()) == schedule["submitted"]
    assert int(stats["completed"].sum()) == schedule["finished"]


def test_replay_iterative_policy_matches_host_driven_rounds():
    """The census-priced (class_cost_fn) trace policy — the
    gtrace12k-coco configuration at toy scale. Rows depend on the
    running-class census, so every window runs the REAL iterative
    transport (VERDICT r4 #1: the closed-form trace policy never
    exercised the solver); the scanned replay must still match the
    host-driven twin round for round, and at 2 slots/machine the
    contended windows must take actual supersteps."""
    from ksched_tpu.costmodels import coco
    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn

    machines, events = synthesize_trace(
        num_machines=12, num_tasks=160, duration_s=120.0,
        mean_runtime_s=60.0, seed=5,
    )
    pen = np.random.default_rng(7).integers(0, 40, (12, 4)).astype(np.int64)
    driver = DeviceTraceReplayDriver(
        machines, slots_per_machine=2, num_jobs_hint=8,
        task_capacity=256, decode_width=None,
        class_cost_fn=coco_device_cost_fn(pen),
        unsched_cost=coco.UNSCHEDULED_COST,
        supersteps=1 << 14,
    )
    assert not driver.cluster.row_constant
    assert not driver.cluster.class_degenerate
    schedule = driver.stage(events, window_s=10.0)
    assert schedule["rounds"] >= 5

    stats = driver.cluster.fetch_stats(driver.replay(schedule))
    assert stats["converged"].all()
    ss = np.asarray(stats["supersteps"])
    assert int(ss.max()) > 0, "census pricing must take iterative supersteps"

    twin, twin_placed = _host_driven_twin(driver, schedule)
    assert stats["placed"].tolist() == twin_placed
    a = driver.cluster.fetch_state()
    b = twin.fetch_state()
    for k in a:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), k


def test_decode_width_defers_then_converges_to_same_final_state():
    """A bounded decode window DEFERS admissions past its width to later
    rounds; it must never change the eventual outcome. Two replays of
    the same staged trace — Tcap-wide decode vs a window narrower than
    the largest admission batch — must end with identical live counts
    and identical per-machine occupancy once the stream drains (the
    semantics the gtrace decode-width configs rely on: admissions
    p50 160 / max 527 against windows of 1024-2048)."""
    machines, events = synthesize_trace(
        num_machines=12, num_tasks=360, duration_s=60.0,
        mean_runtime_s=15.0, seed=9,
    )

    def run(width):
        driver = DeviceTraceReplayDriver(
            machines, slots_per_machine=4, num_jobs_hint=4,
            task_capacity=512, decode_width=width,
        )
        sch = driver.stage(events, window_s=1.0)
        stats = driver.replay(sch, seed=0)
        got = driver.cluster.fetch_stats(stats)
        assert got["converged"].all()
        st = {k: np.asarray(v) for k, v in driver.cluster.fetch_state().items()}
        placed_total = int(np.asarray(got["placed"]).sum())
        return st, placed_total, sch

    st_full, placed_full, sch = run(None)
    # width 4 is far under the per-window admission peaks of this trace
    assert int(sch["adm_n"].max()) > 4
    st_narrow, placed_narrow, _ = run(4)
    # same eventual world: live set and occupancy agree exactly (the
    # narrow decode may place the same task in a later round, but the
    # trace ends drained)
    assert int(st_full["live"].sum()) == int(st_narrow["live"].sum())
    on_full = st_full["live"] & (st_full["pu"] >= 0)
    on_narrow = st_narrow["live"] & (st_narrow["pu"] >= 0)
    assert int(on_full.sum()) == int(on_narrow.sum())
    # placements may land on different-but-equivalent rows; per-PU
    # occupancy histograms must match EXACTLY (ADVICE r5 #1: the old
    # sum() comparison was implied by the count assert above and
    # vacuous — per-PU equality does hold on this trace)
    num_pus = len(st_full["pu_running"])
    m_full = np.bincount(
        np.clip(st_full["pu"][on_full], 0, num_pus - 1), minlength=num_pus
    )
    m_narrow = np.bincount(
        np.clip(st_narrow["pu"][on_narrow], 0, num_pus - 1), minlength=num_pus
    )
    assert np.array_equal(m_full, m_narrow)
