"""Native C++ MCMF backend tests: parity against the Python oracle on
hand-built and randomized instances, plus warm-start reuse across rounds.

Role parity: the reference ships no in-process solver at all — its tests
need the Flowlessly binary on disk (SURVEY §4). Here the native backend
is a first-class, testable library.
"""

import numpy as np
import pytest

from ksched_tpu.graph.device_export import FlowProblem
from ksched_tpu.solver import ReferenceSolver
from ksched_tpu.solver.native import NativeSolver

from test_solver_oracle import make_problem


@pytest.fixture(params=["ssp", "cost_scaling"])
def native(request):
    return NativeSolver(algorithm=request.param)


def test_single_path(native):
    p = make_problem(4, {1: 1, 3: -1}, [(1, 2, 0, 1, 2), (2, 3, 0, 1, 3)])
    r = native.solve(p)
    assert r.objective == 5
    assert list(r.flow) == [1, 1]


def test_chooses_cheaper_path(native):
    p = make_problem(
        4, {1: 1, 3: -1}, [(1, 3, 0, 1, 10), (1, 2, 0, 1, 2), (2, 3, 0, 1, 3)]
    )
    r = native.solve(p)
    assert r.objective == 5


def test_unsched_escape(native):
    arcs = [
        (1, 3, 0, 1, 2),
        (2, 3, 0, 1, 2),
        (3, 4, 0, 1, 0),
        (4, 6, 0, 1, 0),
        (1, 7, 0, 1, 5),
        (2, 7, 0, 1, 5),
        (7, 6, 0, 2, 0),
    ]
    p = make_problem(8, {1: 1, 2: 1, 6: -2}, arcs)
    r = native.solve(p)
    assert r.objective == 7


def test_negative_costs(native):
    p = make_problem(
        4, {1: 1, 3: -1}, [(1, 2, 0, 1, -2), (2, 3, 0, 1, 3), (1, 3, 0, 1, 5)]
    )
    r = native.solve(p)
    assert r.objective == 1


def test_lower_bound_fold(native):
    p = make_problem(
        4, {1: 1, 3: -1}, [(1, 2, 1, 1, 7), (2, 3, 0, 1, 0), (1, 3, 0, 1, 1)]
    )
    r = native.solve(p)
    assert r.total_flow(p)[0] == 1
    assert r.objective == 7


def _random_scheduling_problem(rng, tasks, machines, slots):
    """Quincy-shaped random instance: tasks -> EC -> machines -> sink,
    with per-task unsched escape. Node 0 is padding."""
    n = 1 + tasks + 1 + machines + 2  # tasks, EC, machines, unsched, sink
    ec = 1 + tasks
    mach0 = ec + 1
    unsched = mach0 + machines
    sink = unsched + 1
    excess = {sink: -tasks}
    arcs = []
    for t in range(tasks):
        tid = 1 + t
        excess[tid] = 1
        arcs.append((tid, ec, 0, 1, int(rng.integers(0, 10))))
        arcs.append((tid, unsched, 0, 1, int(rng.integers(20, 40))))
        # a couple of direct preference arcs
        for m in rng.choice(machines, size=2, replace=False):
            arcs.append((tid, mach0 + int(m), 0, 1, int(rng.integers(0, 5))))
    for m in range(machines):
        arcs.append((ec, mach0 + m, 0, slots, int(rng.integers(0, 8))))
        arcs.append((mach0 + m, sink, 0, slots, 0))
    arcs.append((unsched, sink, 0, tasks, 0))
    return make_problem(n, excess, arcs)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_parity_with_oracle(native, seed):
    rng = np.random.default_rng(seed)
    p = _random_scheduling_problem(rng, tasks=30, machines=6, slots=3)
    r_native = native.solve(p)
    r_oracle = ReferenceSolver().solve(p)
    assert r_native.objective == r_oracle.objective
    # feasible flow draining all supply: net outflow == excess everywhere
    out = np.zeros(p.num_nodes, np.int64)
    np.add.at(out, p.src, r_native.flow)
    np.subtract.at(out, p.dst, r_native.flow)
    assert (out == p.excess[: p.num_nodes]).all()
    assert (r_native.flow >= 0).all()
    assert (r_native.flow <= p.cap).all()


def test_warm_start_across_rounds():
    rng = np.random.default_rng(7)
    solver = NativeSolver(algorithm="cost_scaling", warm_start=True)
    p = _random_scheduling_problem(rng, tasks=40, machines=8, slots=3)
    r1 = solver.solve(p)
    # re-solve the same instance warm: same objective
    r2 = solver.solve(p)
    assert r1.objective == r2.objective
    oracle = ReferenceSolver().solve(p)
    assert r1.objective == oracle.objective
    solver.reset()
    r3 = solver.solve(p)
    assert r3.objective == oracle.objective


def test_unbalanced_rejected():
    p = make_problem(3, {1: 2, 2: -1}, [(1, 2, 0, 2, 1)])
    with pytest.raises(RuntimeError, match="unbalanced"):
        NativeSolver().solve(p)


def test_infeasible_rejected():
    # supply cut off from demand
    p = make_problem(4, {1: 1, 3: -1}, [(1, 2, 0, 1, 1)])
    with pytest.raises(RuntimeError, match="infeasible"):
        NativeSolver(algorithm="cost_scaling").solve(p)
